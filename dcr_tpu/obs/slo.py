"""dcr-slo: declarative SLO engine with multi-window burn-rate alerting.

PR 19 left the live provenance plane (serve -> ingest -> WAL -> compaction
-> ANN -> /check) observable but unjudged: gauges exist, nothing says
"healthy" or "breached", and recall is a one-shot bench number. This
module is the judgment layer — the classic SRE multi-window burn-rate
alert (Google SRE workbook ch. 5) over the telemetry the fleet already
scrapes:

- an **objective** is one signal + target + direction (``kind="min"``:
  the value must stay at or above target, e.g. availability;
  ``kind="max"``: at or below, e.g. shed rate);
- every supervisor monitor tick feeds one sample per objective; a sample
  is *bad* when it violates the target. The **burn rate** over a window
  is ``bad_fraction / budget`` — burn 1.0 means the objective is
  consuming its error budget exactly as fast as allowed;
- the state machine is ``ok -> warn`` when the SHORT window burns past
  ``warn_burn``, ``-> breach`` only when BOTH windows burn past
  ``breach_burn`` (a lone spike cannot breach: the long window vetoes
  it), and back to ``ok`` once the short burn drops below
  ``recover_burn`` (< warn_burn — hysteresis, no flapping at the
  threshold);
- state is continuously exported as ``dcr_slo_burn_rate_<objective>``,
  ``dcr_slo_state_<objective>`` (0 ok / 1 warn / 2 breach) and
  ``dcr_slo_breach_total`` metrics, every transition emits a
  ``slo/breach`` / ``slo/recover`` trace event (tools/trace_report
  renders the breach timeline), and a breach sustained past
  ``dump_after_s`` dumps the flight recorder — the post-mortem exists
  even when nobody was watching.

The engine is deliberately passive and clock-injectable: it never sleeps,
never scrapes, never spawns a thread — the supervisor's existing monitor
loop calls :meth:`SloEngine.observe` with the signal snapshot it already
has, and tests drive breach -> recover with an explicit ``now``.

``GET /slo`` on the serve front end returns :meth:`SloEngine.doc`;
``dcr-status`` (cli/status.py) renders it and exits 1 on any breach.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from dcr_tpu.core import tracing

log = logging.getLogger("dcr_tpu")

# objective states, exported as the dcr_slo_state_* gauge value
OK = "ok"
WARN = "warn"
BREACH = "breach"
_STATE_CODE = {OK: 0, WARN: 1, BREACH: 2}


def parse_exposition(text: str) -> dict[str, float]:
    """Unlabeled Prometheus text (one worker's own registry dump) ->
    ``{metric_name: value}``. Comment/blank lines and labeled series
    (histogram quantiles) are skipped — the SLO signals are all plain
    counters/gauges. Unparseable sample values are skipped, never raised:
    a half-written scrape must not take down the monitor loop."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#") or "{" in line:
            continue
        name, _, value = line.rpartition(" ")
        if not name:
            continue
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


@dataclass
class SloObjective:
    """One declarative objective: a named signal judged against a target.

    ``kind="min"`` breaches when the value drops BELOW target
    (availability, recall, coverage); ``kind="max"`` when it rises ABOVE
    (queue wait, shed rate, lag, staleness)."""

    name: str
    signal: str          # key into the signals dict observe() receives
    kind: str            # "min" | "max"
    target: float
    description: str = ""

    def __post_init__(self) -> None:
        if self.kind not in ("min", "max"):
            raise ValueError(
                f"objective {self.name}: kind must be 'min' or 'max', "
                f"got {self.kind!r}")

    def bad(self, value: float) -> bool:
        return value < self.target if self.kind == "min" \
            else value > self.target


class _ObjectiveState:
    """Per-objective sample window + state machine (engine-internal)."""

    def __init__(self, obj: SloObjective):
        self.obj = obj
        self.samples: deque = deque()   # (ts, value, bad)
        self.state = OK
        self.breach_since: Optional[float] = None
        self.breach_total = 0
        self.last_value: Optional[float] = None
        self.burn_short = 0.0
        self.burn_long = 0.0

    def burn(self, now: float, window_s: float, budget: float) -> float:
        lo = now - window_s
        n = bad = 0
        for ts, _, is_bad in self.samples:
            if ts >= lo:
                n += 1
                bad += is_bad
        return (bad / n) / budget if n else 0.0


class SloEngine:
    """Evaluate a set of :class:`SloObjective` against per-tick signal
    snapshots. Thread-safe (`observe` from the monitor loop, `doc` from
    HTTP handler threads); ``now`` is injectable for deterministic tests.
    """

    def __init__(self, cfg, objectives: list[SloObjective]):
        self.cfg = cfg
        self._lock = threading.Lock()
        self._objs = {o.name: _ObjectiveState(o) for o in objectives}
        if len(self._objs) != len(objectives):
            raise ValueError("duplicate objective names")
        self.breach_total = 0
        self._dumped_for: set[str] = set()
        # export the initial all-ok state immediately: a scrape between
        # boot and the first monitor tick must see the series, not a gap
        reg = tracing.registry()
        reg.counter("slo/breach_total")
        for name in self._objs:
            reg.gauge(f"slo/burn_rate/{name}").set(0.0)
            reg.gauge(f"slo/state/{name}").set(0)

    def objectives(self) -> list[SloObjective]:
        return [s.obj for s in self._objs.values()]

    # -- evaluation (one call per monitor tick) ------------------------------

    def observe(self, signals: dict[str, Optional[float]],
                now: Optional[float] = None) -> None:
        """Feed one snapshot. A missing/None signal contributes no sample
        for that objective this tick (the window drains by time, so a
        signal that stops reporting decays toward recovery rather than
        latching its last verdict)."""
        now = time.time() if now is None else float(now)
        c = self.cfg
        with self._lock:
            for st in self._objs.values():
                obj = st.obj
                value = signals.get(obj.signal)
                if value is not None:
                    st.last_value = float(value)
                    st.samples.append((now, float(value),
                                       obj.bad(float(value))))
                lo = now - c.long_window_s
                while st.samples and st.samples[0][0] < lo:
                    st.samples.popleft()
                st.burn_short = st.burn(now, c.short_window_s, c.budget)
                st.burn_long = st.burn(now, c.long_window_s, c.budget)
                self._step_state(st, now)
                reg = tracing.registry()
                reg.gauge(f"slo/burn_rate/{obj.name}").set(st.burn_short)
                reg.gauge(f"slo/state/{obj.name}").set(
                    _STATE_CODE[st.state])

    def _step_state(self, st: _ObjectiveState, now: float) -> None:
        """ok -> warn -> breach -> ok transitions for one objective.
        Caller holds the lock; events/dumps fire inline (tracing never
        blocks)."""
        c = self.cfg
        obj = st.obj
        if st.state != BREACH:
            if (st.burn_short >= c.breach_burn
                    and st.burn_long >= c.breach_burn):
                st.state = BREACH
                st.breach_since = now
                st.breach_total += 1
                self.breach_total += 1
                reg = tracing.registry()
                reg.counter("slo/breach_total").inc()
                reg.counter(f"slo/breach_total/{obj.name}").inc()
                tracing.event("slo/breach", objective=obj.name,
                              value=st.last_value, target=obj.target,
                              kind=obj.kind,
                              burn_short=round(st.burn_short, 4),
                              burn_long=round(st.burn_long, 4))
                log.warning("slo: BREACH %s — value=%s target=%s "
                            "(burn %.2f/%.2f)", obj.name, st.last_value,
                            obj.target, st.burn_short, st.burn_long)
            elif st.state == OK and st.burn_short >= c.warn_burn:
                st.state = WARN
            elif st.state == WARN and st.burn_short < c.warn_burn:
                st.state = OK
        else:
            if st.burn_short <= c.recover_burn:
                duration = now - (st.breach_since or now)
                st.state = OK
                st.breach_since = None
                tracing.event("slo/recover", objective=obj.name,
                              value=st.last_value, target=obj.target,
                              breach_s=round(duration, 3),
                              burn_short=round(st.burn_short, 4))
                log.warning("slo: recovered %s after %.1fs", obj.name,
                            duration)
            elif (c.dump_after_s >= 0
                    and now - (st.breach_since or now) >= c.dump_after_s
                    and obj.name not in self._dumped_for):
                # sustained breach: leave the post-mortem while the
                # signals that caused it are still in the ring. Once per
                # objective per process (dump_flight_recorder itself is
                # additionally first-dump-wins).
                self._dumped_for.add(obj.name)
                tracing.dump_flight_recorder(
                    f"slo_breach_sustained: {obj.name}",
                    extra={"slo": self._doc_locked(now)})

    # -- introspection (GET /slo, dcr-status) --------------------------------

    def breached(self) -> bool:
        with self._lock:
            return any(s.state == BREACH for s in self._objs.values())

    def doc(self) -> dict:
        with self._lock:
            return self._doc_locked(time.time())

    def _doc_locked(self, now: float) -> dict:
        objectives = {}
        worst = OK
        for name, st in self._objs.items():
            obj = st.obj
            if _STATE_CODE[st.state] > _STATE_CODE[worst]:
                worst = st.state
            objectives[name] = {
                "state": st.state,
                "kind": obj.kind,
                "target": obj.target,
                "value": st.last_value,
                "burn_short": round(st.burn_short, 4),
                "burn_long": round(st.burn_long, 4),
                "samples": len(st.samples),
                "breach_total": st.breach_total,
                "breach_for_s": (round(now - st.breach_since, 3)
                                 if st.breach_since is not None else 0.0),
                "description": obj.description,
            }
        return {"enabled": True, "state": worst,
                "breach_total": self.breach_total,
                "windows_s": [self.cfg.short_window_s,
                              self.cfg.long_window_s],
                "objectives": objectives}


def default_objectives(cfg) -> list[SloObjective]:
    """The standard objective set for a serve fleet, derived from a
    :class:`~dcr_tpu.core.config.ServeConfig`: objectives whose plane is
    not configured (no ingest, no ANN tier, no risk index) or whose
    target is disabled (<= 0) are simply absent — an objective that can
    never have a signal must not sit at burn 0 pretending to be met."""
    s = cfg.slo
    out: list[SloObjective] = []
    if s.availability_min > 0:
        out.append(SloObjective(
            "availability", "availability", "min", s.availability_min,
            "alive worker slots with a FRESH scrape / total slots"))
    if cfg.fleet.slo_queue_wait_p99_s > 0:
        out.append(SloObjective(
            "queue_wait_p99_s", "queue_wait_p99_s", "max",
            cfg.fleet.slo_queue_wait_p99_s,
            "request queue-wait p99 (same target admission sheds on)"))
    if s.shed_rate_max > 0:
        out.append(SloObjective(
            "shed_rate", "shed_rate", "max", s.shed_rate_max,
            "shed / (accepted + shed) over the tick window, not lifetime"))
    risk_on = bool(cfg.risk.store_dir or cfg.risk.index_path)
    if cfg.ingest.enabled and s.ingest_lag_s_max > 0:
        out.append(SloObjective(
            "ingest_lag_s", "ingest_lag_s", "max", s.ingest_lag_s_max,
            "max(queue ack lag, wall age of oldest acked-but-unfolded row)"))
    if cfg.risk.ann and s.ann_staleness_rows_max > 0:
        out.append(SloObjective(
            "ann_staleness_rows", "ann_staleness_rows", "max",
            s.ann_staleness_rows_max,
            "store rows (committed + tail) not yet folded into IVF lists"))
    if cfg.risk.ann and s.recall_min > 0:
        out.append(SloObjective(
            "recall", "recall", "min", s.recall_min,
            "rolling online recall@k of the ANN path vs the shadow-exact "
            "oracle (obs/recall_probe.py)"))
    if risk_on and s.coverage_min > 0:
        out.append(SloObjective(
            "coverage", "coverage", "min", s.coverage_min,
            "copy-risk-scored generations / completed generations per "
            "tick window"))
    return out

"""dcr-slo: sampled shadow-exact recall probe for the online ANN path.

PR 19's recall number is a one-shot bench artifact (BENCH_ANN.json):
true the day it was banked, silent the day the corpus drifts. This
module turns recall into a *continuously observed* quantity with zero
extra infrastructure — the same pattern as the SSCD fidelity gates,
applied online:

every Nth ANN scoring call, the probe re-runs the SAME queries through
the SAME :class:`~dcr_tpu.search.annindex.AnnEngine` at full probe
width (``nprobe = n_lists``). With every inverted list probed the
candidate set is the whole committed corpus, and the engine's f32
re-rank is already exact — so the full-probe answer IS the exact
``search/topk`` oracle, bit-for-bit, with no second engine, no second
compiled program, and no second copy of the store in memory. The live
WAL tail (already scanned exactly by ``query_rows``) merges into both
sides identically, so the probe measures exactly what production
shortlists miss: candidates pruned by the IVF probe.

Results feed a rolling window published as ``dcr_ann_recall_online_pct``
(+ ``..._samples`` so consumers can weight it); the fleet scrape carries
it to the supervisor, where the ``recall`` SLO objective judges it. The
``recall_degrade`` fault kind corrupts the production shortlist the
probe sees — driving the objective ok -> breach -> ok deterministically
in tests without ever touching real traffic.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Sequence

import numpy as np

from dcr_tpu.core import tracing
from dcr_tpu.utils import faults


class RecallProbe:
    """Rolling online recall@k, sampled once per ``every_n`` ANN calls.

    Thread-safe: serve handler threads share one probe per risk index.
    The expensive full-probe query runs OUTSIDE the lock — only the
    sampling decision and the rolling-window update are serialized, so a
    probe in flight never blocks the next scoring call's sampling check.
    """

    def __init__(self, *, every_n: int = 32, k: int = 10,
                 window: int = 64):
        if every_n < 1:
            raise ValueError(f"every_n must be >= 1, got {every_n}")
        if k < 1 or window < 1:
            raise ValueError(f"k/window must be >= 1, got {k}/{window}")
        self.every_n = int(every_n)
        self.k = int(k)
        self.window = int(window)
        self._lock = threading.Lock()
        self._calls = 0
        self._probes = 0
        self._recalls: deque = deque(maxlen=self.window)

    # -- hot-path entry ------------------------------------------------------

    def observe(self, engine, q: np.ndarray, ann_keys: np.ndarray, *,
                tail_feats: Optional[np.ndarray] = None,
                tail_keys: Optional[Sequence[str]] = None) -> Optional[float]:
        """Called by the copy-risk scorer with the production shortlist it
        just computed. Returns this sample's recall when this call was
        probed, else None (not a probe tick). ``ann_keys`` is the [n, K]
        key table the ANN path (including any tail merge) produced."""
        with self._lock:
            self._calls += 1
            if (self._calls - 1) % self.every_n != 0:
                return None
            self._probes += 1
            probe_idx = self._probes
        if faults.fire("recall_degrade", probe=probe_idx):
            # corrupt the shortlist the probe judges (production results
            # are untouched): every key misses, recall pins to 0
            ann_keys = np.full_like(np.asarray(ann_keys, dtype=object),
                                    "__recall_degrade__")
        truth_keys = self._oracle(engine, q, tail_feats, tail_keys)
        recall = self._recall_at_k(ann_keys, truth_keys)
        with self._lock:
            self._recalls.append(recall)
            rolling = sum(self._recalls) / len(self._recalls)
            samples = len(self._recalls)
        reg = tracing.registry()
        reg.gauge("ann/recall_online_pct").set(int(round(rolling * 100)))
        reg.gauge("ann/recall_online_samples").set(samples)
        reg.counter("ann/recall_probe_total").inc()
        tracing.event("ann/recall_probe", k=self.k,
                      queries=int(np.asarray(q).shape[0]),
                      recall=round(recall, 4), rolling=round(rolling, 4),
                      samples=samples)
        return recall

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _oracle(engine, q, tail_feats, tail_keys) -> np.ndarray:
        """Exact top-k key table: full-probe IVF (candidate set = whole
        committed corpus, re-rank already exact) merged with the exact
        tail scan — the shadow oracle."""
        e_scores, e_keys = engine.query(q, nprobe=engine.ann.n_lists)
        if tail_feats is not None and len(tail_feats):
            from dcr_tpu.search.shardindex import merge_topk

            t_scores, t_keys = engine.query_rows(q, tail_feats, tail_keys)
            _, e_keys = merge_topk(e_scores, e_keys, t_scores, t_keys)
        return e_keys

    def _recall_at_k(self, ann_keys: np.ndarray,
                     truth_keys: np.ndarray) -> float:
        """Same set-overlap recall as
        :func:`dcr_tpu.search.annindex.spot_check_recall` — one
        definition of recall across bench and online paths."""
        ann_keys = np.asarray(ann_keys, dtype=object)
        kk = min(self.k, ann_keys.shape[1], truth_keys.shape[1])
        hits = total = 0
        for arow, erow in zip(ann_keys, truth_keys):
            truth = set(x for x in erow[:kk] if x)
            if not truth:
                continue
            hits += len(truth & set(arow[:kk].tolist()))
            total += len(truth)
        return hits / total if total else 1.0

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            samples = len(self._recalls)
            rolling = (sum(self._recalls) / samples) if samples else None
            return {"calls": self._calls, "probes": self._probes,
                    "samples": samples, "every_n": self.every_n,
                    "k": self.k,
                    "rolling_recall": (round(rolling, 4)
                                       if rolling is not None else None)}

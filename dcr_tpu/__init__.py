"""dcr_tpu — a TPU-native (JAX/XLA/pjit/Pallas) framework with the capabilities of
somepago/DCR: Stable-Diffusion finetuning under controlled data-duplication and
caption-conditioning regimes, train/inference-time copying mitigations, bulk
jit-compiled sampling, and end-to-end replication measurement (SSCD/DINO/CLIP
similarity, FID, CLIP alignment, complexity correlations, LAION-scale search).

Ground-up idiomatic JAX design — see SURVEY.md for the structural analysis of the
reference that this framework reproduces capability-for-capability.

Layering (SURVEY.md §1):
  L0/L1  core/, parallel/   config, rng, precision, checkpoint, metrics, mesh, dist
  L2     data/              datasets, captions, duplication, tokenizer, loader
  L3     models/, ops/      Flax module zoo + Pallas kernels
  L4     diffusion/, sampling/, eval/, search/   workload libraries
  L5     cli/               thin command-line entry points
"""

__version__ = "0.1.0"

# Lazy public API: importing dcr_tpu stays cheap (no jax/orbax cost) until a
# symbol is actually used.
_PUBLIC = {
    "TrainConfig": "dcr_tpu.core.config",
    "SampleConfig": "dcr_tpu.core.config",
    "EvalConfig": "dcr_tpu.core.config",
    "SearchConfig": "dcr_tpu.core.config",
    "ModelConfig": "dcr_tpu.core.config",
    "MeshConfig": "dcr_tpu.core.config",
    "FaultToleranceConfig": "dcr_tpu.core.config",
    "QuarantineManifest": "dcr_tpu.core.resilience",
    "retry_call": "dcr_tpu.core.resilience",
    "Trainer": "dcr_tpu.diffusion.trainer",
    "generate": "dcr_tpu.sampling.pipeline",
    "run_eval": "dcr_tpu.eval.runner",
    "make_mesh": "dcr_tpu.parallel.mesh",
    "build_backbone": "dcr_tpu.eval.runner",
    "DINO_ARCHS": "dcr_tpu.models.vit",
    "load_tokenizer": "dcr_tpu.data.tokenizer",
    "flash_attention": "dcr_tpu.ops.flash_attention",
    "ring_self_attention": "dcr_tpu.ops.ring_attention",
    "ulysses_self_attention": "dcr_tpu.ops.ulysses_attention",
    "adamw8bit": "dcr_tpu.core.adam8bit",
}


def __getattr__(name):
    if name in _PUBLIC:
        import importlib

        return getattr(importlib.import_module(_PUBLIC[name]), name)
    raise AttributeError(f"module 'dcr_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_PUBLIC))

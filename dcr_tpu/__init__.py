"""dcr_tpu — a TPU-native (JAX/XLA/pjit/Pallas) framework with the capabilities of
somepago/DCR: Stable-Diffusion finetuning under controlled data-duplication and
caption-conditioning regimes, train/inference-time copying mitigations, bulk
jit-compiled sampling, and end-to-end replication measurement (SSCD/DINO/CLIP
similarity, FID, CLIP alignment, complexity correlations, LAION-scale search).

Ground-up idiomatic JAX design — see SURVEY.md for the structural analysis of the
reference that this framework reproduces capability-for-capability.

Layering (SURVEY.md §1):
  L0/L1  core/, parallel/   config, rng, precision, checkpoint, metrics, mesh, dist
  L2     data/              datasets, captions, duplication, tokenizer, loader
  L3     models/, ops/      Flax module zoo + Pallas kernels
  L4     diffusion/, sampling/, eval/, search/   workload libraries
  L5     cli/               thin command-line entry points
"""

__version__ = "0.1.0"

"""Vision Transformer (DINO-style) — alternative copy-detection backbone.

Capability-equivalent of the reference's in-repo DINO ViT zoo (dino_vits.py:
PatchEmbed 153-168, Attention 105-129, Block 132-150, VisionTransformer 171-275
incl. positional-embedding interpolation 213-233 and get_intermediate_layers
267-275, hub constructors 340-487). Implemented fresh in Flax/NHWC; pretrained
DINO checkpoints load through models/convert.py.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from dcr_tpu.ops.attention import dot_product_attention


class PatchEmbed(nn.Module):
    patch_size: int = 16
    embed_dim: int = 768
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        p = self.patch_size
        # VALID: non-divisible inputs floor to the same grid the torch
        # reference's padding-0 Conv2d produces (SAME would emit ceil and
        # desync from the positional table)
        x = nn.Conv(self.embed_dim, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, name="proj")(x)
        b, h, w, c = x.shape
        return x.reshape(b, h * w, c)


class ViTBlock(nn.Module):
    num_heads: int
    mlp_ratio: float = 4.0
    dtype: jnp.dtype = jnp.float32
    # "gelu" (DINO) or "quick_gelu" (OpenAI CLIP: x·σ(1.702x)); real CLIP
    # weights silently drift without the matching activation.
    act: str = "gelu"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        d = x.shape[-1]
        head_dim = d // self.num_heads
        h = nn.LayerNorm(dtype=self.dtype, name="norm1")(x)
        qkv = nn.Dense(3 * d, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, s, _ = q.shape
        reshape = lambda t: t.reshape(b, s, self.num_heads, head_dim)
        out = dot_product_attention(reshape(q), reshape(k), reshape(v),
                                    use_flash=False)
        out = nn.Dense(d, dtype=self.dtype, name="proj")(out.reshape(b, s, d))
        x = x + out
        h = nn.LayerNorm(dtype=self.dtype, name="norm2")(x)
        h = nn.Dense(int(d * self.mlp_ratio), dtype=self.dtype, name="fc1")(h)
        if self.act == "quick_gelu":
            h = h * jax.nn.sigmoid(1.702 * h)
        else:
            h = nn.gelu(h)
        h = nn.Dense(d, dtype=self.dtype, name="fc2")(h)
        return x + h


def _bicubic_axis(out_size: int, in_size: int, scale: float):
    """Tap indices [4, out] and weights [4, out] for one axis of torch's
    `F.interpolate(mode="bicubic", align_corners=False, scale_factor=scale)`:
    source coord (dst+0.5)/scale - 0.5, cubic-convolution kernel A=-0.75,
    border-clamped taps. Computed host-side (static shapes) at trace time."""
    import numpy as np

    a = -0.75
    k_near = lambda x: ((a + 2) * x - (a + 3)) * x * x + 1        # |x| <= 1
    k_far = lambda x: ((a * x - 5 * a) * x + 8 * a) * x - 4 * a   # 1 < |x| < 2
    src = (np.arange(out_size) + 0.5) / scale - 0.5
    i0 = np.floor(src).astype(np.int64)
    t = src - i0
    weights = np.stack([k_far(t + 1.0), k_near(t), k_near(1.0 - t),
                        k_far(2.0 - t)])
    idx = np.stack([i0 - 1, i0, i0 + 1, i0 + 2]).clip(0, in_size - 1)
    return idx, weights


def interpolate_pos_embed(pos_embed: jax.Array, num_patches: int,
                          grid_hw: tuple[int, int],
                          pixel_hw: Optional[tuple[int, int]] = None) -> jax.Array:
    """Bicubic interpolation of the patch position table to a new grid —
    lets one checkpoint serve any input resolution. Numerically identical to
    the reference's torch path (dino_vits.py:213-233: scale factors carry the
    +0.1 anti-rounding nudge and feed the coordinate mapping directly);
    verified against executed reference code in tests/test_reference_parity.py."""
    cls_pos, patch_pos = pos_embed[:, :1], pos_embed[:, 1:]
    n_orig = patch_pos.shape[1]
    h, w = grid_hw
    # the skip condition tests *pixel* squareness, not grid squareness
    # (reference `npatch == N and w == h` on pixel dims, dino_vits.py:216):
    # a non-square pixel input whose floored grid happens square (e.g. 32x39,
    # patch 8) still takes the (near-identity) interpolation path
    ph, pw = pixel_hw if pixel_hw is not None else (h, w)
    if n_orig == num_patches and ph == pw:
        return pos_embed
    side = int(math.sqrt(n_orig))
    grid = patch_pos.reshape(side, side, -1)
    iy, wy = _bicubic_axis(h, side, (h + 0.1) / side)
    ix, wx = _bicubic_axis(w, side, (w + 0.1) / side)
    wy = jnp.asarray(wy, grid.dtype)
    wx = jnp.asarray(wx, grid.dtype)
    rows = jnp.einsum("kh,khsd->hsd", wy, grid[iy])        # [h, side, D]
    out = jnp.einsum("kw,hkwd->hwd", wx, rows[:, ix])      # [h, w, D]
    return jnp.concatenate([cls_pos, out.reshape(1, h * w, -1)], axis=1)


class VisionTransformer(nn.Module):
    patch_size: int = 16
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    # sizes the positional table, like the reference's img_size arg
    # (dino_vits.py:176-187); other input sizes interpolate from it
    img_size: int = 224
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, *,
                 return_layers: Optional[int] = None) -> jax.Array | list[jax.Array]:
        """x: [B,H,W,3]. Returns the CLS embedding [B, D] (the reference uses
        the cls token as the retrieval feature), or the last `return_layers`
        full hidden states (get_intermediate_layers equivalent)."""
        b, h, w, _ = x.shape
        gh, gw = h // self.patch_size, w // self.patch_size
        tokens = PatchEmbed(self.patch_size, self.embed_dim, dtype=self.dtype,
                            name="patch_embed")(x)
        cls = self.param("cls_token", nn.initializers.zeros, (1, 1, self.embed_dim))
        max_grid = self.img_size // self.patch_size
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, max_grid * max_grid + 1, self.embed_dim))
        pos = interpolate_pos_embed(pos, gh * gw, (gh, gw), pixel_hw=(h, w))
        tokens = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, self.embed_dim)),
                                  tokens], axis=1) + pos.astype(self.dtype)
        outputs = []
        for i in range(self.depth):
            tokens = ViTBlock(self.num_heads, self.mlp_ratio, dtype=self.dtype,
                              name=f"blocks_{i}")(tokens)
            outputs.append(tokens)
        norm = nn.LayerNorm(dtype=self.dtype, name="norm")
        if return_layers:
            return [norm(o) for o in outputs[-return_layers:]]
        return norm(tokens)[:, 0]


# constructors mirroring the reference's zoo (dino_vits.py:278-296,340-487)
def vit_tiny(patch_size: int = 16, **kw) -> VisionTransformer:
    return VisionTransformer(patch_size, 192, 12, 3, **kw)


def vit_small(patch_size: int = 16, **kw) -> VisionTransformer:
    return VisionTransformer(patch_size, 384, 12, 6, **kw)


def vit_base(patch_size: int = 16, **kw) -> VisionTransformer:
    return VisionTransformer(patch_size, 768, 12, 12, **kw)


def _dino_resnet50():
    # plain torchvision-resnet50 trunk + avgpool, the reference's
    # dino_resnet50 hub entry (dino_vits.py:438-452); pretrained weights load
    # via convert.convert_resnet50
    from dcr_tpu.models.resnet import ResNet50Classifier

    return ResNet50Classifier()


def _xcit(name: str):
    # imported lazily so vit.py stays importable without pulling resnet
    # (xcit reuses FrozenBatchNorm) until an xcit arch is actually built
    from dcr_tpu.models import xcit

    size, patch = name.rsplit("_p", 1)
    ctor = {"xcit_small_12": xcit.xcit_small_12,
            "xcit_medium_24": xcit.xcit_medium_24}[size]
    return ctor(patch_size=int(patch))


DINO_ARCHS = {
    "dino_vits16": lambda: vit_small(16),
    "dino_vits8": lambda: vit_small(8),
    "dino_vitb16": lambda: vit_base(16),
    "dino_vitb8": lambda: vit_base(8),
    # CIFAR-10-finetuned ViT-B/16 (reference dino_vits.py:340-360): same
    # architecture, different checkpoint; pos-embed interpolation handles the
    # 32px grid
    "dino_vitb_cifar10": lambda: vit_base(16),
    "dino_resnet50": _dino_resnet50,
    # XCiT hub family (reference dino_vits.py:413-487)
    "dino_xcit_small_12_p16": lambda: _xcit("xcit_small_12_p16"),
    "dino_xcit_small_12_p8": lambda: _xcit("xcit_small_12_p8"),
    "dino_xcit_medium_24_p16": lambda: _xcit("xcit_medium_24_p16"),
    "dino_xcit_medium_24_p8": lambda: _xcit("xcit_medium_24_p8"),
}

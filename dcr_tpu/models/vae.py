"""AutoencoderKL — latent-space VAE (SD architecture), TPU-native Flax, NHWC.

Capability-equivalent of the frozen diffusers AutoencoderKL the reference uses to
map pixels↔latents (diff_train.py:383,620-621 encode ×0.18215; decode inside the
sampling pipeline). Encoder outputs a diagonal Gaussian (mean, logvar); training
samples it with an explicit rng key (the reference relies on torch global rng).
"""

from __future__ import annotations

from typing import NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from dcr_tpu.core.config import ModelConfig
from dcr_tpu.models import layers as L


class DiagonalGaussian(NamedTuple):
    mean: jax.Array
    logvar: jax.Array

    def sample(self, key: jax.Array) -> jax.Array:
        std = jnp.exp(0.5 * jnp.clip(self.logvar, -30.0, 20.0))
        return self.mean + std * jax.random.normal(key, self.mean.shape, self.mean.dtype)

    def mode(self) -> jax.Array:
        return self.mean


class Encoder(nn.Module):
    config: ModelConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        block_out = cfg.vae_block_out_channels
        groups = min(cfg.norm_num_groups, block_out[0])
        h = nn.Conv(block_out[0], (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name="conv_in")(x.astype(self.dtype))
        for i, ch in enumerate(block_out):
            for j in range(cfg.vae_layers_per_block):
                h = L.ResnetBlock2D(ch, num_groups=groups, epsilon=1e-6, dtype=self.dtype,
                                    name=f"down_{i}_res_{j}")(h)
            if i < len(block_out) - 1:
                # asymmetric (0,1,0,1) pad + VALID conv, matching diffusers'
                # AutoencoderKL encoder (Downsample2D with padding=0).
                h = L.Downsample2D(ch, asymmetric_pad=True, dtype=self.dtype,
                                   name=f"down_{i}_downsample")(h)
        ch = block_out[-1]
        h = L.ResnetBlock2D(ch, num_groups=groups, epsilon=1e-6, dtype=self.dtype, name="mid_res_0")(h)
        h = L.AttentionBlock2D(num_groups=groups, dtype=self.dtype, name="mid_attn")(h)
        h = L.ResnetBlock2D(ch, num_groups=groups, epsilon=1e-6, dtype=self.dtype, name="mid_res_1")(h)
        h = L.GroupNorm(groups, epsilon=1e-6, name="conv_norm_out")(h)
        h = nn.silu(h)
        h = nn.Conv(2 * cfg.vae_latent_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv_out")(h)
        # diffusers applies an extra 1x1 "quant_conv"
        h = nn.Conv(2 * cfg.vae_latent_channels, (1, 1), dtype=self.dtype,
                    name="quant_conv")(h)
        return h.astype(jnp.float32)


class Decoder(nn.Module):
    config: ModelConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, z: jax.Array) -> jax.Array:
        cfg = self.config
        block_out = cfg.vae_block_out_channels
        groups = min(cfg.norm_num_groups, block_out[0])
        z = nn.Conv(cfg.vae_latent_channels, (1, 1), dtype=self.dtype,
                    name="post_quant_conv")(z.astype(self.dtype))
        ch = block_out[-1]
        h = nn.Conv(ch, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name="conv_in")(z)
        h = L.ResnetBlock2D(ch, num_groups=groups, epsilon=1e-6, dtype=self.dtype, name="mid_res_0")(h)
        h = L.AttentionBlock2D(num_groups=groups, dtype=self.dtype, name="mid_attn")(h)
        h = L.ResnetBlock2D(ch, num_groups=groups, epsilon=1e-6, dtype=self.dtype, name="mid_res_1")(h)
        for i, ch in enumerate(reversed(block_out)):
            for j in range(cfg.vae_layers_per_block + 1):
                h = L.ResnetBlock2D(ch, num_groups=groups, epsilon=1e-6, dtype=self.dtype,
                                    name=f"up_{i}_res_{j}")(h)
            if i < len(block_out) - 1:
                h = L.Upsample2D(ch, dtype=self.dtype, name=f"up_{i}_upsample")(h)
        h = L.GroupNorm(groups, epsilon=1e-6, name="conv_norm_out")(h)
        h = nn.silu(h)
        h = nn.Conv(3, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype,
                    name="conv_out")(h)
        return h.astype(jnp.float32)


class AutoencoderKL(nn.Module):
    config: ModelConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.encoder = Encoder(self.config, dtype=self.dtype)
        self.decoder = Decoder(self.config, dtype=self.dtype)

    def encode(self, x: jax.Array) -> DiagonalGaussian:
        moments = self.encoder(x)
        mean, logvar = jnp.split(moments, 2, axis=-1)
        return DiagonalGaussian(mean, logvar)

    def decode(self, z: jax.Array) -> jax.Array:
        return self.decoder(z)

    def __call__(self, x: jax.Array, key: jax.Array) -> jax.Array:
        dist = self.encode(x)
        return self.decode(dist.sample(key))


def vae_scale_factor(cfg: ModelConfig) -> int:
    """Pixel-to-latent downscale (8 for the SD 4-block VAE)."""
    return 2 ** (len(cfg.vae_block_out_channels) - 1)


def init_vae(cfg: ModelConfig, key: jax.Array, dtype=jnp.float32,
             model: "AutoencoderKL | None" = None):
    model = model if model is not None else AutoencoderKL(cfg, dtype=dtype)
    px = vae_scale_factor(cfg) * cfg.sample_size
    x = jnp.zeros((1, px, px, 3))
    params = model.init(key, x, jax.random.key(0))["params"]
    return model, params

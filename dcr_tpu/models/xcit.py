"""XCiT (Cross-Covariance Image Transformer) — DINO copy-detection backbone.

The reference's last backbone family: its hub constructors
(/root/reference/dino_vits.py:413-487) pull ``xcit_small_12_p16`` /
``xcit_small_12_p8`` / ``xcit_medium_24_p16`` / ``xcit_medium_24_p8`` from
``facebookresearch/xcit`` and load DINO-pretrained state dicts. There is no
XCiT source in the reference repo, so this is implemented fresh from the
published architecture (El-Nouby et al., "XCiT: Cross-Covariance Image
Transformers", NeurIPS 2021) in Flax/NHWC:

- ``ConvPatchEmbed``: a stride-2 conv3x3+BN stack (4 stages for /16,
  3 for /8) instead of one big patchify conv;
- ``PositionalEncodingFourier``: 2D sinusoidal encoding projected by a
  1x1 conv (the only learned part of the positional signal);
- ``XCA``: attention over the *channel* dimension — L2-normalised q/k,
  a learned per-head temperature, d×d attention (linear in tokens);
- ``LPI``: depthwise 3x3 → GELU → BN → depthwise 3x3 on the token grid;
- two CaiT-style class-attention layers that inject the CLS token after
  the trunk (only CLS attends; patch tokens ride along).

Pretrained hub checkpoints load through models/convert.convert_xcit;
activation parity vs an independent torch twin is tested in
tests/test_torch_parity.py.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from dcr_tpu.models.resnet import FrozenBatchNorm


def _gelu(x: jax.Array) -> jax.Array:
    # exact erf form — torch nn.GELU's default; the tanh approximation
    # drifts ~1e-3 and fails twin parity at fp32 tolerances
    return nn.gelu(x, approximate=False)


class PositionalEncodingFourier(nn.Module):
    """Sinusoidal 2D position signal -> 1x1 conv projection to ``dim``.

    Matches the hub models' ``pos_embeder`` (their spelling): per-axis
    cumulative positions normalised to (0, 2π], sin/cos over a
    ``hidden_dim``-frequency bank with temperature 10000, y-bank then
    x-bank concatenated, projected channelwise."""

    dim: int
    hidden_dim: int = 32
    temperature: float = 10000.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h: int, w: int) -> jax.Array:
        eps = 1e-6
        scale = 2 * math.pi
        y = (jnp.arange(1, h + 1, dtype=jnp.float32) / (h + eps) * scale)
        x = (jnp.arange(1, w + 1, dtype=jnp.float32) / (w + eps) * scale)
        dim_t = jnp.arange(self.hidden_dim, dtype=jnp.float32)
        dim_t = self.temperature ** (2 * (dim_t // 2) / self.hidden_dim)

        def bank(pos):  # [L] -> [L, hidden_dim], interleaved sin/cos
            t = pos[:, None] / dim_t                       # [L, hidden]
            pair = jnp.stack([jnp.sin(t[:, 0::2]), jnp.cos(t[:, 1::2])], axis=-1)
            return pair.reshape(pos.shape[0], self.hidden_dim)

        py = jnp.broadcast_to(bank(y)[:, None, :], (h, w, self.hidden_dim))
        px = jnp.broadcast_to(bank(x)[None, :, :], (h, w, self.hidden_dim))
        pos = jnp.concatenate([py, px], axis=-1)[None]     # [1, h, w, 2*hidden]
        pos = nn.Conv(self.dim, (1, 1), dtype=self.dtype,
                      name="token_projection")(pos.astype(self.dtype))
        return pos.reshape(1, h * w, self.dim)


class ConvPatchEmbed(nn.Module):
    """Stride-2 conv3x3+BN tower: 4 stages for patch 16, 3 for patch 8.
    Channel plan doubles up to ``embed_dim`` (dim/8 -> dim/4 -> dim/2 -> dim
    for /16), GELU between stages, no activation after the last."""

    patch_size: int = 16
    embed_dim: int = 384
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.patch_size == 16:
            widths = (self.embed_dim // 8, self.embed_dim // 4,
                      self.embed_dim // 2, self.embed_dim)
        elif self.patch_size == 8:
            widths = (self.embed_dim // 4, self.embed_dim // 2, self.embed_dim)
        else:
            raise ValueError(f"XCiT patch_size must be 8 or 16, got {self.patch_size}")
        for i, width in enumerate(widths):
            if i:
                x = _gelu(x)
            # torch Conv2d(k=3, s=2, p=1): one leading + one trailing pad row
            x = nn.Conv(width, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                        use_bias=False, dtype=self.dtype, name=f"conv{i}")(x)
            x = FrozenBatchNorm(name=f"bn{i}")(x)
        b, h, w, c = x.shape
        return x.reshape(b, h * w, c), (h, w)


class XCA(nn.Module):
    """Cross-covariance attention: softmax over a d_head×d_head channel
    Gram matrix of L2-normalised q/k, scaled by a learned per-head
    temperature — cost linear in token count."""

    num_heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, n, c = x.shape
        d = c // self.num_heads
        temperature = self.param("temperature", nn.initializers.ones,
                                 (self.num_heads, 1, 1))
        qkv = nn.Dense(3 * c, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # [B, N, C] -> [B, heads, d_head, N]: attention lives on channels
        shape = lambda t: t.reshape(b, n, self.num_heads, d).transpose(0, 2, 3, 1)
        q, k, v = shape(q), shape(k), shape(v)
        norm = lambda t: t / jnp.maximum(
            jnp.linalg.norm(t, axis=-1, keepdims=True), 1e-12)  # torch F.normalize
        attn = jnp.einsum("bhdn,bhen->bhde", norm(q), norm(k)) * temperature
        attn = jax.nn.softmax(attn, axis=-1)
        out = jnp.einsum("bhde,bhen->bhdn", attn, v)
        out = out.transpose(0, 3, 1, 2).reshape(b, n, c)
        return nn.Dense(c, dtype=self.dtype, name="proj")(out)


class LPI(nn.Module):
    """Local Patch Interaction: two depthwise 3x3 convs over the token grid
    with GELU+BN between — XCiT's substitute for token mixing."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, hw: tuple[int, int]) -> jax.Array:
        b, n, c = x.shape
        h, w = hw
        g = x.reshape(b, h, w, c)
        dw = lambda name: nn.Conv(c, (3, 3), padding=((1, 1), (1, 1)),
                                  feature_group_count=c, dtype=self.dtype,
                                  name=name)
        g = dw("conv1")(g)
        g = _gelu(g)
        g = FrozenBatchNorm(name="bn")(g)
        g = dw("conv2")(g)
        return g.reshape(b, n, c)


class Mlp(nn.Module):
    hidden: int
    out: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Dense(self.hidden, dtype=self.dtype, name="fc1")(x)
        x = _gelu(x)
        return nn.Dense(self.out, dtype=self.dtype, name="fc2")(x)


class XCABlock(nn.Module):
    """Trunk layer: LayerScale'd XCA, LPI, and MLP residual branches
    (order: attention, local patch interaction, MLP)."""

    num_heads: int
    mlp_ratio: float = 4.0
    eta: float = 1.0     # LayerScale init (1.0 small_12, 1e-5 medium_24)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, hw: tuple[int, int]) -> jax.Array:
        c = x.shape[-1]
        gamma = lambda name: self.param(
            name, nn.initializers.constant(self.eta), (c,))
        h = XCA(self.num_heads, dtype=self.dtype, name="attn")(
            nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, name="norm1")(x))
        x = x + gamma("gamma1") * h
        h = LPI(dtype=self.dtype, name="local_mp")(
            nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, name="norm3")(x), hw)
        x = x + gamma("gamma3") * h
        h = Mlp(int(c * self.mlp_ratio), c, dtype=self.dtype, name="mlp")(
            nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, name="norm2")(x))
        return x + gamma("gamma2") * h


class ClassAttention(nn.Module):
    """CaiT-style class attention: only the CLS query attends over all
    tokens; the non-CLS rows of the (normed) input pass through unchanged."""

    num_heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, n, c = x.shape
        d = c // self.num_heads
        qkv = nn.Dense(3 * c, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = lambda t: t.reshape(b, n, self.num_heads, d).transpose(0, 2, 1, 3)
        q, k, v = shape(q), shape(k), shape(v)          # [B, h, N, d]
        qc = q[:, :, :1]                                 # CLS query only
        attn = jnp.sum(qc * k, axis=-1) * (d ** -0.5)    # [B, h, N]
        attn = jax.nn.softmax(attn, axis=-1)
        cls = jnp.einsum("bhn,bhnd->bhd", attn, v).reshape(b, 1, c)
        cls = nn.Dense(c, dtype=self.dtype, name="proj")(cls)
        return jnp.concatenate([cls, x[:, 1:]], axis=1)


class ClassAttentionBlock(nn.Module):
    """Class-attention layer with ``tokens_norm=True`` (the hub models'
    setting): norm2 runs over every token, and the final residual adds the
    post-norm tokens back onto the [γ2·MLP(CLS), patches] concat — patch
    tokens pick up a doubling the original keeps; CLS output is what DINO
    consumes and LayerNorm's scale invariance makes the next block blind
    to the factor, but we reproduce it exactly for hub-weight fidelity."""

    num_heads: int
    mlp_ratio: float = 4.0
    eta: float = 1.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        gamma = lambda name: self.param(
            name, nn.initializers.constant(self.eta), (c,))
        h = ClassAttention(self.num_heads, dtype=self.dtype, name="attn")(
            nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, name="norm1")(x))
        x = x + gamma("gamma1") * h
        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, name="norm2")(x)
        cls = gamma("gamma2") * Mlp(int(c * self.mlp_ratio), c,
                                    dtype=self.dtype, name="mlp")(x[:, :1])
        return x + jnp.concatenate([cls, x[:, 1:]], axis=1)


class XCiT(nn.Module):
    """Full XCiT trunk; returns the CLS embedding [B, embed_dim] (head is
    identity for ``num_classes=0``, the reference's retrieval setting).

    Token count is H/p * W/p for any input divisible by stage strides —
    no positional table to interpolate (the Fourier encoding is generated
    for the actual grid), so arbitrary eval resolutions come for free."""

    patch_size: int = 16
    embed_dim: int = 384
    depth: int = 12
    num_heads: int = 8
    mlp_ratio: float = 4.0
    cls_attn_layers: int = 2
    eta: float = 1.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b = x.shape[0]
        tokens, hw = ConvPatchEmbed(self.patch_size, self.embed_dim,
                                    dtype=self.dtype, name="patch_embed")(x)
        pos = PositionalEncodingFourier(self.embed_dim, dtype=self.dtype,
                                        name="pos_embeder")(*hw)
        tokens = tokens + pos
        for i in range(self.depth):
            tokens = XCABlock(self.num_heads, self.mlp_ratio, eta=self.eta,
                              dtype=self.dtype, name=f"blocks_{i}")(tokens, hw)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, self.embed_dim))
        tokens = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, self.embed_dim)).astype(self.dtype),
             tokens], axis=1)
        for i in range(self.cls_attn_layers):
            tokens = ClassAttentionBlock(
                self.num_heads, self.mlp_ratio, eta=self.eta,
                dtype=self.dtype, name=f"cls_attn_blocks_{i}")(tokens)
        return nn.LayerNorm(epsilon=1e-6, dtype=self.dtype, name="norm")(tokens)[:, 0]


# hub-model hyperparameters (facebookresearch/xcit registry as consumed by
# the reference's dino_xcit_* constructors, dino_vits.py:413-487)
def xcit_small_12(patch_size: int = 16, **kw) -> XCiT:
    return XCiT(patch_size, 384, 12, 8, eta=1.0, **kw)


def xcit_medium_24(patch_size: int = 16, **kw) -> XCiT:
    return XCiT(patch_size, 512, 24, 8, eta=1e-5, **kw)

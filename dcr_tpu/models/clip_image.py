"""CLIP image tower (ViT-B/16) + full CLIP scorer.

The reference uses OpenAI CLIP twice: as a retrieval backbone
(diff_retrieval.py:268-275, encode_image in utils_ret.py:686) and for the
gen/train CLIP alignment score (utils_ret.py:1045-1066: cosine similarity of
L2-normalized image and caption embeddings from ViT-B/16). The text tower
reuses dcr_tpu.models.clip_text with CLIP-B dimensions plus the text projection.
"""

from __future__ import annotations

from typing import NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from dcr_tpu.core.config import ModelConfig
from dcr_tpu.models.clip_text import CLIPTextModel
from dcr_tpu.models.vit import ViTBlock


def clip_b16_text_config(vocab_size: int = 49408) -> ModelConfig:
    """CLIP ViT-B/16 text tower dims (512 wide, 12 layers, 8 heads)."""
    return ModelConfig(text_vocab_size=vocab_size, text_hidden_size=512,
                       text_layers=12, text_heads=8, text_max_length=77,
                       text_act="quick_gelu")


class CLIPImageTower(nn.Module):
    """Pre-LN ViT with class embedding and projection to the shared space."""

    patch_size: int = 16
    width: int = 768
    layers: int = 12
    heads: int = 12
    embed_dim: int = 512
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        """[B,H,W,3] in [0,1] -> [B, embed_dim] (unnormalized)."""
        mean = jnp.asarray([0.48145466, 0.4578275, 0.40821073], x.dtype)
        std = jnp.asarray([0.26862954, 0.26130258, 0.27577711], x.dtype)
        x = (x - mean) / std
        p = self.patch_size
        x = nn.Conv(self.width, (p, p), strides=(p, p), use_bias=False,
                    dtype=self.dtype, name="patch_embed")(x)
        b, gh, gw, _ = x.shape
        tokens = x.reshape(b, gh * gw, self.width)
        cls = self.param("class_embedding", nn.initializers.normal(0.02),
                         (self.width,))
        tokens = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, self.width)), tokens], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, tokens.shape[1], self.width))
        tokens = tokens + pos.astype(self.dtype)
        tokens = nn.LayerNorm(dtype=self.dtype, name="ln_pre")(tokens)
        for i in range(self.layers):
            # OpenAI CLIP towers use QuickGELU, not exact GELU
            tokens = ViTBlock(self.heads, dtype=self.dtype, act="quick_gelu",
                              name=f"blocks_{i}")(tokens)
        cls_out = nn.LayerNorm(dtype=self.dtype, name="ln_post")(tokens[:, 0])
        proj = self.param("proj", nn.initializers.normal(0.02),
                          (self.width, self.embed_dim))
        return cls_out @ proj.astype(self.dtype)


class CLIPScorer(NamedTuple):
    """Bundled towers for the alignment score."""

    image_tower: CLIPImageTower
    text_tower: CLIPTextModel
    text_config: ModelConfig

    def image_features(self, params, images) -> jax.Array:
        feats = self.image_tower.apply({"params": params["image"]}, images)
        return feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)

    def text_features(self, params, input_ids) -> jax.Array:
        out = self.text_tower.apply({"params": params["text"]}, input_ids)
        proj = params["text_projection"]
        feats = out.pooled @ proj
        return feats / jnp.linalg.norm(feats, axis=-1, keepdims=True)

    def score(self, params, images, input_ids) -> jax.Array:
        """Per-pair cosine similarity (the reference's (img*txt).sum(-1),
        utils_ret.py:1061)."""
        return jnp.sum(self.image_features(params, images)
                       * self.text_features(params, input_ids), axis=-1)


def make_clip_scorer(embed_dim: int = 512) -> CLIPScorer:
    tcfg = clip_b16_text_config()
    return CLIPScorer(
        image_tower=CLIPImageTower(embed_dim=embed_dim),
        text_tower=CLIPTextModel(tcfg),
        text_config=tcfg,
    )


def init_clip_scorer(key: jax.Array, scorer: CLIPScorer, image_size: int = 224):
    k1, k2, k3 = jax.random.split(key, 3)
    image_params = scorer.image_tower.init(
        k1, jnp.zeros((1, image_size, image_size, 3)))["params"]
    text_params = scorer.text_tower.init(
        k2, jnp.zeros((1, scorer.text_config.text_max_length), jnp.int32))["params"]
    proj = jax.random.normal(
        k3, (scorer.text_config.text_hidden_size,
             scorer.image_tower.embed_dim)) * 0.02
    return {"image": image_params, "text": text_params, "text_projection": proj}

"""InceptionV3 (pool3, 2048-d) for FID — TF-FID-faithful architecture in Flax.

Re-implements the network of the reference's metrics/inception.py (16-163,
224-341): torchvision InceptionV3 sliced at pool3, with the pytorch-fid patches
that reproduce the original TF-FID network — average pools that exclude padding
(FIDInceptionA/C/E_1) and a max-pool branch in the last block (FIDInceptionE_2)
— plus the 299px resize and (0,1)→(−1,1) input scaling (146-153). Weights from
the pt_inception-2015-12-05 checkpoint load via models/convert.py; FID numbers
are only comparable across frameworks when those converted weights are used.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from dcr_tpu.models.resnet import FrozenBatchNorm


class ConvBN(nn.Module):
    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: tuple[tuple[int, int], tuple[int, int]] = ((0, 0), (0, 0))
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    name="conv")(x)
        x = FrozenBatchNorm(epsilon=1e-3, name="bn")(x)
        return nn.relu(x)


def _avg_pool_exclude_pad(x: jax.Array) -> jax.Array:
    """3x3 stride-1 avg pool, padding excluded from the divisor (the TF-FID
    behavior the pytorch-fid patches exist for)."""
    ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
    summed = nn.pool(x, 0.0, jax.lax.add, (3, 3), (1, 1), ((1, 1), (1, 1)))
    counts = nn.pool(ones, 0.0, jax.lax.add, (3, 3), (1, 1), ((1, 1), (1, 1)))
    return summed / counts


class InceptionA(nn.Module):
    pool_features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b1 = ConvBN(64, (1, 1), dtype=self.dtype, name="branch1x1")(x)
        b5 = ConvBN(48, (1, 1), dtype=self.dtype, name="branch5x5_1")(x)
        b5 = ConvBN(64, (5, 5), padding=((2, 2), (2, 2)), dtype=self.dtype, name="branch5x5_2")(b5)
        b3 = ConvBN(64, (1, 1), dtype=self.dtype, name="branch3x3dbl_1")(x)
        b3 = ConvBN(96, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype, name="branch3x3dbl_2")(b3)
        b3 = ConvBN(96, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype, name="branch3x3dbl_3")(b3)
        bp = _avg_pool_exclude_pad(x)
        bp = ConvBN(self.pool_features, (1, 1), dtype=self.dtype, name="branch_pool")(bp)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b3 = ConvBN(384, (3, 3), strides=(2, 2), dtype=self.dtype, name="branch3x3")(x)
        bd = ConvBN(64, (1, 1), dtype=self.dtype, name="branch3x3dbl_1")(x)
        bd = ConvBN(96, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype, name="branch3x3dbl_2")(bd)
        bd = ConvBN(96, (3, 3), strides=(2, 2), dtype=self.dtype, name="branch3x3dbl_3")(bd)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    c7: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c7 = self.c7
        b1 = ConvBN(192, (1, 1), dtype=self.dtype, name="branch1x1")(x)
        b7 = ConvBN(c7, (1, 1), dtype=self.dtype, name="branch7x7_1")(x)
        b7 = ConvBN(c7, (1, 7), padding=((0, 0), (3, 3)), dtype=self.dtype, name="branch7x7_2")(b7)
        b7 = ConvBN(192, (7, 1), padding=((3, 3), (0, 0)), dtype=self.dtype, name="branch7x7_3")(b7)
        bd = ConvBN(c7, (1, 1), dtype=self.dtype, name="branch7x7dbl_1")(x)
        bd = ConvBN(c7, (7, 1), padding=((3, 3), (0, 0)), dtype=self.dtype, name="branch7x7dbl_2")(bd)
        bd = ConvBN(c7, (1, 7), padding=((0, 0), (3, 3)), dtype=self.dtype, name="branch7x7dbl_3")(bd)
        bd = ConvBN(c7, (7, 1), padding=((3, 3), (0, 0)), dtype=self.dtype, name="branch7x7dbl_4")(bd)
        bd = ConvBN(192, (1, 7), padding=((0, 0), (3, 3)), dtype=self.dtype, name="branch7x7dbl_5")(bd)
        bp = _avg_pool_exclude_pad(x)
        bp = ConvBN(192, (1, 1), dtype=self.dtype, name="branch_pool")(bp)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b3 = ConvBN(192, (1, 1), dtype=self.dtype, name="branch3x3_1")(x)
        b3 = ConvBN(320, (3, 3), strides=(2, 2), dtype=self.dtype, name="branch3x3_2")(b3)
        b7 = ConvBN(192, (1, 1), dtype=self.dtype, name="branch7x7x3_1")(x)
        b7 = ConvBN(192, (1, 7), padding=((0, 0), (3, 3)), dtype=self.dtype, name="branch7x7x3_2")(b7)
        b7 = ConvBN(192, (7, 1), padding=((3, 3), (0, 0)), dtype=self.dtype, name="branch7x7x3_3")(b7)
        b7 = ConvBN(192, (3, 3), strides=(2, 2), dtype=self.dtype, name="branch7x7x3_4")(b7)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    pool_mode: str  # "avg" (Mixed_7b, exclude-pad) | "max" (Mixed_7c, FID quirk)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b1 = ConvBN(320, (1, 1), dtype=self.dtype, name="branch1x1")(x)
        b3 = ConvBN(384, (1, 1), dtype=self.dtype, name="branch3x3_1")(x)
        b3a = ConvBN(384, (1, 3), padding=((0, 0), (1, 1)), dtype=self.dtype, name="branch3x3_2a")(b3)
        b3b = ConvBN(384, (3, 1), padding=((1, 1), (0, 0)), dtype=self.dtype, name="branch3x3_2b")(b3)
        b3 = jnp.concatenate([b3a, b3b], axis=-1)
        bd = ConvBN(448, (1, 1), dtype=self.dtype, name="branch3x3dbl_1")(x)
        bd = ConvBN(384, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype, name="branch3x3dbl_2")(bd)
        bda = ConvBN(384, (1, 3), padding=((0, 0), (1, 1)), dtype=self.dtype, name="branch3x3dbl_3a")(bd)
        bdb = ConvBN(384, (3, 1), padding=((1, 1), (0, 0)), dtype=self.dtype, name="branch3x3dbl_3b")(bd)
        bd = jnp.concatenate([bda, bdb], axis=-1)
        if self.pool_mode == "max":
            bp = nn.max_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)))
        else:
            bp = _avg_pool_exclude_pad(x)
        bp = ConvBN(192, (1, 1), dtype=self.dtype, name="branch_pool")(bp)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3FID(nn.Module):
    """Input: [B,H,W,3] in [0,1] (resized to 299 internally when needed).
    Output: pool3 activations [B, 2048]."""

    resize_input: bool = True
    normalize_input: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.resize_input and x.shape[1:3] != (299, 299):
            # antialias=False matches the reference's F.interpolate bilinear
            # (metrics/inception.py:149-151), which never low-pass filters —
            # with the default antialias=True, FID on >299px inputs would
            # silently diverge from reference numbers
            x = jax.image.resize(x, (x.shape[0], 299, 299, 3),
                                 method="bilinear", antialias=False)
        if self.normalize_input:
            x = x * 2.0 - 1.0
        x = ConvBN(32, (3, 3), strides=(2, 2), dtype=self.dtype, name="Conv2d_1a_3x3")(x)
        x = ConvBN(32, (3, 3), dtype=self.dtype, name="Conv2d_2a_3x3")(x)
        x = ConvBN(64, (3, 3), padding=((1, 1), (1, 1)), dtype=self.dtype, name="Conv2d_2b_3x3")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = ConvBN(80, (1, 1), dtype=self.dtype, name="Conv2d_3b_1x1")(x)
        x = ConvBN(192, (3, 3), dtype=self.dtype, name="Conv2d_4a_3x3")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = InceptionA(32, dtype=self.dtype, name="Mixed_5b")(x)
        x = InceptionA(64, dtype=self.dtype, name="Mixed_5c")(x)
        x = InceptionA(64, dtype=self.dtype, name="Mixed_5d")(x)
        x = InceptionB(dtype=self.dtype, name="Mixed_6a")(x)
        x = InceptionC(128, dtype=self.dtype, name="Mixed_6b")(x)
        x = InceptionC(160, dtype=self.dtype, name="Mixed_6c")(x)
        x = InceptionC(160, dtype=self.dtype, name="Mixed_6d")(x)
        x = InceptionC(192, dtype=self.dtype, name="Mixed_6e")(x)
        x = InceptionD(dtype=self.dtype, name="Mixed_7a")(x)
        x = InceptionE("avg", dtype=self.dtype, name="Mixed_7b")(x)
        x = InceptionE("max", dtype=self.dtype, name="Mixed_7c")(x)
        return jnp.mean(x, axis=(1, 2))  # adaptive avg pool -> [B, 2048]


def init_inception(key: jax.Array, image_size: int = 75):
    """image_size=75 keeps test-time init cheap; the net is shape-polymorphic
    down to the 8x8 grid minimum (75 -> 1x1 at pool3 is below; use >= 75)."""
    model = InceptionV3FID(resize_input=False)
    params = model.init(key, jnp.zeros((1, image_size, image_size, 3)))["params"]
    return model, params

"""ResNet-50 + GeM/projection head — the SSCD copy-detection embedder.

The reference ships SSCD only as opaque TorchScript archives
(diff_retrieval.py:277-285, embedding_search/utils.py:17-25); every headline
copying metric (sim_gt_05pc etc.) is computed on its 512-d embeddings. Here the
architecture is explicit Flax (SSCD = ResNet-50 trunk → GeM pooling → linear
projection, per the SSCD paper "A Self-Supervised Descriptor for Image Copy
Detection", Pizzi et al. 2022), with a weight converter
(models/convert.py) for loading the published checkpoints.

NHWC; BatchNorm runs in inference mode (frozen stats) — these backbones are
feature extractors, never trained here.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class FrozenBatchNorm(nn.Module):
    """Inference-only batchnorm: y = (x - mean) / sqrt(var + eps) * scale + bias.
    Stats are parameters (loaded from a converted checkpoint), never updated."""

    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,))
        bias = self.param("bias", nn.initializers.zeros, (c,))
        mean = self.param("mean", nn.initializers.zeros, (c,))
        var = self.param("var", nn.initializers.ones, (c,))
        inv = jax.lax.rsqrt(var + self.epsilon) * scale
        return x * inv + (bias - mean * inv)


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with expansion 4."""

    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        residual = x
        out = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype,
                      name="conv1")(x)
        out = FrozenBatchNorm(name="bn1")(out)
        out = nn.relu(out)
        out = nn.Conv(self.features, (3, 3), strides=(self.strides, self.strides),
                      padding=((1, 1), (1, 1)), use_bias=False, dtype=self.dtype,
                      name="conv2")(out)
        out = FrozenBatchNorm(name="bn2")(out)
        out = nn.relu(out)
        out = nn.Conv(self.features * 4, (1, 1), use_bias=False, dtype=self.dtype,
                      name="conv3")(out)
        out = FrozenBatchNorm(name="bn3")(out)
        if residual.shape[-1] != self.features * 4 or self.strides != 1:
            residual = nn.Conv(self.features * 4, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype,
                               name="downsample_conv")(x)
            residual = FrozenBatchNorm(name="downsample_bn")(residual)
        return nn.relu(out + residual)


class ResNet50(nn.Module):
    """Standard ResNet-50 trunk -> [B, H/32, W/32, 2048] feature map."""

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=((3, 3), (3, 3)),
                    use_bias=False, dtype=self.dtype, name="conv1")(x)
        x = FrozenBatchNorm(name="bn1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        features = 64
        for stage, num_blocks in enumerate(self.stage_sizes):
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = Bottleneck(features, strides=strides, dtype=self.dtype,
                               name=f"layer{stage + 1}_{block}")(x)
            features *= 2
        return x


def gem_pool(x: jax.Array, p: float = 3.0, eps: float = 1e-6) -> jax.Array:
    """Generalized-mean pooling over spatial dims: (mean(x^p))^(1/p)."""
    x = jnp.clip(x, eps, None) ** p
    return jnp.mean(x, axis=(1, 2)) ** (1.0 / p)


class SSCDModel(nn.Module):
    """SSCD descriptor: ResNet-50 -> GeM(p=3) -> Linear(2048->embed_dim).

    Outputs are NOT L2-normalized here; the eval stage normalizes explicitly
    (mirroring the reference's F.normalize at diff_retrieval.py:388-389 — the
    raw TorchScript output is likewise unnormalized)."""

    embed_dim: int = 512
    gem_p: float = 3.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        feats = ResNet50(dtype=self.dtype, name="backbone")(x)
        pooled = gem_pool(feats, self.gem_p)
        return nn.Dense(self.embed_dim, use_bias=True, dtype=self.dtype,
                        name="embeddings")(pooled)


class ResNet50Classifier(nn.Module):
    """ResNet-50 with avgpool head (the reference's plain torchvision resnet50
    option for dino_resnet50-style backbones)."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        feats = ResNet50(dtype=self.dtype, name="backbone")(x)
        return jnp.mean(feats, axis=(1, 2))


def init_sscd(key: jax.Array, embed_dim: int = 512, image_size: int = 224):
    model = SSCDModel(embed_dim=embed_dim)
    params = model.init(key, jnp.zeros((1, image_size, image_size, 3)))["params"]
    return model, params

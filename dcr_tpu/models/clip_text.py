"""CLIP text encoder (OpenCLIP ViT-H text tower shape for SD-2.1), Flax.

Capability-equivalent of the frozen transformers CLIPTextModel the reference
conditions on (diff_train.py:376-381, 636). Pre-LN transformer with causal mask;
returns the full hidden-state stack so callers can pick the final or penultimate
layer (SD-2.x conditions on the penultimate).
"""

from __future__ import annotations

from typing import NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from dcr_tpu.core.config import ModelConfig


class CLIPTextOutput(NamedTuple):
    last_hidden_state: jax.Array        # [B, S, D] after final LN
    penultimate_hidden_state: jax.Array  # [B, S, D] layer -2, final-LN applied
    pooled: jax.Array                    # [B, D] EOT-token embedding


class CLIPLayer(nn.Module):
    heads: int
    dtype: jnp.dtype = jnp.float32
    # "gelu" (SD-2.x OpenCLIP ViT-H tower) or "quick_gelu" (OpenAI CLIP-B/L)
    act: str = "gelu"

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array) -> jax.Array:
        d = x.shape[-1]
        h = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="ln1")(x)
        h = nn.MultiHeadDotProductAttention(num_heads=self.heads, dtype=self.dtype,
                                            deterministic=True, name="attn")(h, mask=mask)
        x = x + h
        h = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="ln2")(x)
        h = nn.Dense(4 * d, dtype=self.dtype, name="fc1")(h)
        if self.act == "quick_gelu":
            h = h * nn.sigmoid(1.702 * h)
        else:
            h = nn.gelu(h, approximate=False)
        h = nn.Dense(d, dtype=self.dtype, name="fc2")(h)
        return x + h


class CLIPTextModel(nn.Module):
    config: ModelConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids: jax.Array) -> CLIPTextOutput:
        cfg = self.config
        b, s = input_ids.shape
        tok = nn.Embed(cfg.text_vocab_size, cfg.text_hidden_size,
                       dtype=self.dtype, name="token_embedding")(input_ids)
        pos = self.param("position_embedding", nn.initializers.normal(0.01),
                         (cfg.text_max_length, cfg.text_hidden_size))
        x = tok + pos[None, :s, :].astype(self.dtype)
        causal = nn.make_causal_mask(input_ids)  # [B, 1, S, S]
        hidden = x
        penultimate = x
        for i in range(cfg.text_layers):
            if i == cfg.text_layers - 1:
                penultimate = hidden
            hidden = CLIPLayer(cfg.text_heads, dtype=self.dtype,
                               act=getattr(cfg, "text_act", "gelu"),
                               name=f"layers_{i}")(hidden, causal)
        ln_final = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="final_layer_norm")
        last = ln_final(hidden)
        penultimate = ln_final(penultimate)
        # pooled = embedding at the EOT token (highest token id = argmax trick,
        # matching CLIP: eot has the largest id in the vocab)
        eot_idx = jnp.argmax(input_ids, axis=-1)
        pooled = jnp.take_along_axis(
            last, eot_idx[:, None, None].astype(jnp.int32), axis=1
        ).squeeze(1)
        return CLIPTextOutput(last.astype(jnp.float32),
                              penultimate.astype(jnp.float32),
                              pooled.astype(jnp.float32))


def init_clip_text(cfg: ModelConfig, key: jax.Array, dtype=jnp.float32,
                   model: "CLIPTextModel | None" = None):
    model = model if model is not None else CLIPTextModel(cfg, dtype=dtype)
    ids = jnp.zeros((1, cfg.text_max_length), jnp.int32)
    params = model.init(key, ids)["params"]
    return model, params

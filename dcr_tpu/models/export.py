"""Flax → diffusers/transformers state-dict exporters (inverse of convert.py).

The reference saves checkpoints with DiffusionPipeline.save_pretrained
(diff_train.py:709-716), so anything in the HF ecosystem can load them. Round 1
exported Flax trees as .npz under an HF-shaped directory — nothing outside this
repo could read it (VERDICT round 1 item 3/4). These exporters emit real torch
layout ([O,I,H,W] convs, [out,in] linears) under the exact diffusers naming so
the exported safetensors are loadable by diffusers/transformers:

- unet_to_diffusers:  UNet2DConditionModel keys (SD-2.x linear-projection
  transformer variant)
- vae_to_diffusers:   AutoencoderKL keys, mid-attention in the 0.14-era
  AttentionBlock naming (query/key/value/proj_attn) that on-hub SD
  checkpoints use — old diffusers loads it directly, new diffusers remaps
- text_to_transformers: CLIPTextModel keys (text_model.* prefix)

Key sets are validated byte-for-byte against the vendored SD-2.1 manifests
(tests/fixtures/sd21_*_keys.json) in tests/test_export.py.
"""

from __future__ import annotations

import re
from typing import Any, Callable

import numpy as np


def _leaves(tree: Any, path: str = ""):
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _leaves(tree[k], f"{path}/{k}" if path else k)
    else:
        yield path, np.asarray(tree)


def _torch_leaf(path: str, value: np.ndarray,
                name_map: Callable[[str], str]) -> tuple[str, np.ndarray]:
    """One Flax leaf -> (torch key, torch-layout array)."""
    parts = path.split("/")
    leaf = parts[-1]
    prefix = name_map("/".join(parts[:-1]))
    if leaf == "kernel":
        if value.ndim == 4:                       # HWIO -> OIHW
            return f"{prefix}.weight", np.transpose(value, (3, 2, 0, 1))
        return f"{prefix}.weight", np.transpose(value, (1, 0))
    if leaf == "scale":
        return f"{prefix}.weight", value
    if leaf == "mean":
        return f"{prefix}.running_mean", value
    if leaf == "var":
        return f"{prefix}.running_var", value
    return f"{prefix}.{leaf}", value


def _tree_to_sd(params: Any, name_map: Callable[[str], str]) -> dict[str, np.ndarray]:
    return dict(_torch_leaf(p, v, name_map) for p, v in _leaves(params))


# ---------------------------------------------------------------------------
# UNet2DCondition -> diffusers UNet2DConditionModel
# ---------------------------------------------------------------------------

def unet_name_map(n_blocks: int) -> Callable[[str], str]:
    def f(p: str) -> str:
        p = re.sub(r"^down_(\d+)_res_(\d+)", r"down_blocks.\1.resnets.\2", p)
        p = re.sub(r"^down_(\d+)_attn_(\d+)", r"down_blocks.\1.attentions.\2", p)
        p = re.sub(r"^down_(\d+)_downsample", r"down_blocks.\1.downsamplers.0", p)
        p = re.sub(r"^up_(\d+)_res_(\d+)",
                   lambda m: f"up_blocks.{n_blocks - 1 - int(m.group(1))}"
                             f".resnets.{m.group(2)}", p)
        p = re.sub(r"^up_(\d+)_attn_(\d+)",
                   lambda m: f"up_blocks.{n_blocks - 1 - int(m.group(1))}"
                             f".attentions.{m.group(2)}", p)
        p = re.sub(r"^up_(\d+)_upsample",
                   lambda m: f"up_blocks.{n_blocks - 1 - int(m.group(1))}"
                             f".upsamplers.0", p)
        p = re.sub(r"^mid_res_(\d)", r"mid_block.resnets.\1", p)
        p = re.sub(r"^mid_attn", r"mid_block.attentions.0", p)
        p = re.sub(r"blocks_(\d+)", r"transformer_blocks.\1", p)
        p = re.sub(r"/(attn\d)/to_out", r"/\1/to_out.0", p)
        p = p.replace("/ff/proj_in", "/ff/net.0.proj")
        p = p.replace("/ff/proj_out", "/ff/net.2")
        p = p.replace("/GroupNorm_0", "")
        return p.replace("/", ".")
    return f


def unet_to_diffusers(params: Any, *, n_blocks: int = 4) -> dict[str, np.ndarray]:
    return _tree_to_sd(params, unet_name_map(n_blocks))


# ---------------------------------------------------------------------------
# AutoencoderKL -> diffusers AutoencoderKL (0.14-era attention naming)
# ---------------------------------------------------------------------------

_VAE_ATTN_OLD = {"to_q": "query", "to_k": "key", "to_v": "value",
                 "to_out": "proj_attn"}


def vae_name_map(p: str) -> str:
    p = re.sub(r"^encoder/down_(\d+)_res_(\d+)",
               r"encoder.down_blocks.\1.resnets.\2", p)
    p = re.sub(r"^encoder/down_(\d+)_downsample",
               r"encoder.down_blocks.\1.downsamplers.0", p)
    p = re.sub(r"^(encoder|decoder)/mid_res_(\d)", r"\1.mid_block.resnets.\2", p)
    p = re.sub(r"^(encoder|decoder)/mid_attn", r"\1.mid_block.attentions.0", p)
    p = re.sub(r"^decoder/up_(\d+)_res_(\d+)", r"decoder.up_blocks.\1.resnets.\2", p)
    p = re.sub(r"^decoder/up_(\d+)_upsample", r"decoder.up_blocks.\1.upsamplers.0", p)
    p = p.replace("encoder/quant_conv", "quant_conv")
    p = p.replace("decoder/post_quant_conv", "post_quant_conv")
    p = re.sub(r"/(to_q|to_k|to_v|to_out)$",
               lambda m: "/" + _VAE_ATTN_OLD[m.group(1)], p)
    p = p.replace("/GroupNorm_0", "")
    return p.replace("/", ".")


def vae_to_diffusers(params: Any) -> dict[str, np.ndarray]:
    return _tree_to_sd(params, vae_name_map)


# ---------------------------------------------------------------------------
# CLIPTextModel (ours) -> transformers CLIPTextModel
# ---------------------------------------------------------------------------

def text_to_transformers(params: Any) -> dict[str, np.ndarray]:
    """Our CLIPTextModel tree -> transformers text_model.* state dict. The
    attention kernels are flax MultiHeadDotProductAttention [D,H,hd] /
    [H,hd,D]; fold the head axes back into [D,D] torch linears."""
    sd: dict[str, np.ndarray] = {}
    p = "text_model."
    sd[f"{p}embeddings.token_embedding.weight"] = np.asarray(
        params["token_embedding"]["embedding"])
    sd[f"{p}embeddings.position_embedding.weight"] = np.asarray(
        params["position_embedding"])
    names = {"query": "q_proj", "key": "k_proj", "value": "v_proj"}
    i = 0
    while f"layers_{i}" in params:
        lp = params[f"layers_{i}"]
        dst = f"{p}encoder.layers.{i}"
        for ours, theirs in (("ln1", "layer_norm1"), ("ln2", "layer_norm2")):
            sd[f"{dst}.{theirs}.weight"] = np.asarray(lp[ours]["scale"])
            sd[f"{dst}.{theirs}.bias"] = np.asarray(lp[ours]["bias"])
        d = np.asarray(lp["attn"]["query"]["kernel"]).shape[0]
        for ours, theirs in names.items():
            w = np.asarray(lp["attn"][ours]["kernel"]).reshape(d, d)  # [D, D] in,out
            b = np.asarray(lp["attn"][ours]["bias"]).reshape(d)
            sd[f"{dst}.self_attn.{theirs}.weight"] = np.transpose(w, (1, 0))
            sd[f"{dst}.self_attn.{theirs}.bias"] = b
        wo = np.asarray(lp["attn"]["out"]["kernel"]).reshape(d, d)     # [in, out]
        sd[f"{dst}.self_attn.out_proj.weight"] = np.transpose(wo, (1, 0))
        sd[f"{dst}.self_attn.out_proj.bias"] = np.asarray(lp["attn"]["out"]["bias"])
        for fc in ("fc1", "fc2"):
            sd[f"{dst}.mlp.{fc}.weight"] = np.transpose(
                np.asarray(lp[fc]["kernel"]), (1, 0))
            sd[f"{dst}.mlp.{fc}.bias"] = np.asarray(lp[fc]["bias"])
        i += 1
    sd[f"{p}final_layer_norm.weight"] = np.asarray(
        params["final_layer_norm"]["scale"])
    sd[f"{p}final_layer_norm.bias"] = np.asarray(
        params["final_layer_norm"]["bias"])
    return sd

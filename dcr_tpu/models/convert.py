"""Torch→Flax weight converters for every pretrained backbone the framework
consumes.

The reference loads all weights from torch artifacts: diffusers SD checkpoints
(diff_train.py:370-408), SSCD TorchScript archives (diff_retrieval.py:277-285),
DINO hub checkpoints (dino_vits.py:340-487), pt_inception FID weights
(metrics/inception.py:219-220), torchvision VGG16 (metrics/ipr.py:41), OpenAI
CLIP. This module maps those state dicts onto our NHWC Flax parameter trees:

    conv   [O,I,H,W] -> [H,W,I,O]
    linear [O,I]     -> [I,O]
    norm scale/bias and BN running stats copy through

Converters take a plain ``{name: np.ndarray}`` state dict (call
:func:`torch_state_dict_to_numpy` on a loaded torch module/TorchScript archive
first, so torch is only required at conversion time, never at run time).
"""

from __future__ import annotations

import logging
import re
from typing import Mapping

import numpy as np

log = logging.getLogger("dcr_tpu")

Arr = np.ndarray
StateDict = Mapping[str, Arr]


def torch_state_dict_to_numpy(module_or_sd) -> dict[str, Arr]:
    """Accepts a torch nn.Module, a TorchScript module, or a state dict."""
    sd = module_or_sd
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    return {k: np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)
            for k, v in sd.items()}


def load_torch_file(path) -> dict[str, Arr]:
    """Checkpoint file of any reference-relevant flavor -> numpy state dict:
    safetensors, torch state-dict .pth/.pt, or a TorchScript archive (the SSCD
    distribution format, diff_retrieval.py:277-285). Single loader shared by
    the checkpoint importer and the eval runner."""
    p = str(path)
    if p.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return load_file(p)
    import torch

    try:
        obj = torch.load(p, map_location="cpu", weights_only=True)
    except Exception as e:
        try:
            obj = torch.jit.load(p, map_location="cpu")
        except Exception as jit_e:
            # keep the original torch.load failure visible — a corrupt or
            # weights_only-incompatible state dict should not surface as a
            # confusing TorchScript error with its real cause discarded
            raise RuntimeError(
                f"{p!r} is neither a loadable state dict ({e!r}) nor a "
                f"TorchScript archive") from jit_e
    return torch_state_dict_to_numpy(obj)


def conv_kernel(w: Arr) -> Arr:
    return np.transpose(w, (2, 3, 1, 0))


def linear_kernel(w: Arr) -> Arr:
    return np.transpose(w, (1, 0))


def _set(tree: dict, path: str, value: Arr) -> None:
    parts = path.split("/")
    cur = tree
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = np.asarray(value)


def _conv(tree: dict, dst: str, sd: StateDict, src: str) -> None:
    _set(tree, f"{dst}/kernel", conv_kernel(sd[f"{src}.weight"]))
    if f"{src}.bias" in sd:
        _set(tree, f"{dst}/bias", sd[f"{src}.bias"])


def _linear(tree: dict, dst: str, sd: StateDict, src: str) -> None:
    _set(tree, f"{dst}/kernel", linear_kernel(sd[f"{src}.weight"]))
    if f"{src}.bias" in sd:
        _set(tree, f"{dst}/bias", sd[f"{src}.bias"])


def _layernorm(tree: dict, dst: str, sd: StateDict, src: str) -> None:
    _set(tree, f"{dst}/scale", sd[f"{src}.weight"])
    _set(tree, f"{dst}/bias", sd[f"{src}.bias"])


def _groupnorm(tree: dict, dst: str, sd: StateDict, src: str) -> None:
    # our GroupNorm wrapper nests flax's GroupNorm as GroupNorm_0
    _set(tree, f"{dst}/GroupNorm_0/scale", sd[f"{src}.weight"])
    _set(tree, f"{dst}/GroupNorm_0/bias", sd[f"{src}.bias"])


def _batchnorm(tree: dict, dst: str, sd: StateDict, src: str) -> None:
    _set(tree, f"{dst}/scale", sd[f"{src}.weight"])
    _set(tree, f"{dst}/bias", sd[f"{src}.bias"])
    _set(tree, f"{dst}/mean", sd[f"{src}.running_mean"])
    _set(tree, f"{dst}/var", sd[f"{src}.running_var"])


# ---------------------------------------------------------------------------
# ResNet-50 / SSCD (TorchScript archives, torchvision naming under `backbone.`)
# ---------------------------------------------------------------------------

def convert_resnet50(sd: StateDict, *, prefix: str = "",
                     stage_sizes=(3, 4, 6, 3)) -> dict:
    t: dict = {}
    _conv(t, "conv1", sd, f"{prefix}conv1")
    _batchnorm(t, "bn1", sd, f"{prefix}bn1")
    for stage, blocks in enumerate(stage_sizes, start=1):
        for b in range(blocks):
            src = f"{prefix}layer{stage}.{b}"
            dst = f"layer{stage}_{b}"
            for c in (1, 2, 3):
                _conv(t, f"{dst}/conv{c}", sd, f"{src}.conv{c}")
                _batchnorm(t, f"{dst}/bn{c}", sd, f"{src}.bn{c}")
            if f"{src}.downsample.0.weight" in sd:
                _conv(t, f"{dst}/downsample_conv", sd, f"{src}.downsample.0")
                _batchnorm(t, f"{dst}/downsample_bn", sd, f"{src}.downsample.1")
    return t


def convert_sscd(sd: StateDict) -> dict:
    """SSCD TorchScript: resnet50 trunk under `backbone.`, projection under
    `embeddings.` (a Linear). Returns params for models.resnet.SSCDModel."""
    sd = dict(sd)
    prefix = "backbone." if any(k.startswith("backbone.") for k in sd) else ""
    out = {"backbone": convert_resnet50(sd, prefix=prefix)}
    emb_key = next((k for k in sd if re.search(r"embeddings?\.(0\.)?weight$", k)
                    and sd[k].ndim == 2), None)
    if emb_key is None:
        raise KeyError("no projection layer found in SSCD state dict")
    bias_key = emb_key.replace("weight", "bias")
    out["embeddings"] = {"kernel": linear_kernel(sd[emb_key])}
    if bias_key in sd:
        out["embeddings"]["bias"] = np.asarray(sd[bias_key])
    else:
        out["embeddings"]["bias"] = np.zeros(sd[emb_key].shape[0], np.float32)
    return out


# ---------------------------------------------------------------------------
# InceptionV3 FID (pt_inception-2015-12-05 naming == our module names)
# ---------------------------------------------------------------------------

def convert_inception_fid(sd: StateDict) -> dict:
    t: dict = {}
    convs = sorted({k[: -len(".conv.weight")] for k in sd
                    if k.endswith(".conv.weight")})
    for name in convs:
        dst = name.replace(".", "/")
        _conv(t, f"{dst}/conv", sd, f"{name}.conv")
        _batchnorm(t, f"{dst}/bn", sd, f"{name}.bn")
    if not t:
        raise KeyError("no Inception conv blocks found in state dict")
    return t


# ---------------------------------------------------------------------------
# VGG16 (torchvision sequential naming)
# ---------------------------------------------------------------------------

def convert_vgg16(sd: StateDict) -> dict:
    t: dict = {}
    conv_indices = sorted(
        {int(m.group(1)) for k in sd
         if (m := re.match(r"features\.(\d+)\.weight", k))})
    for i, idx in enumerate(conv_indices):
        _conv(t, f"conv_{i}", sd, f"features.{idx}")
    # fc1 consumes the flattened 7x7x512 feature map. torch flattens CHW
    # (c*49 + h*7 + w) while our NHWC model flattens HWC (h*3584 + w*512 + c):
    # reorder fc1's input columns accordingly before transposing.
    w1 = sd["classifier.0.weight"]                       # [4096, 25088] (CHW cols)
    w1 = w1.reshape(-1, 512, 7, 7).transpose(0, 2, 3, 1)  # -> [4096, 7, 7, 512]
    _set(t, "fc1/kernel", linear_kernel(w1.reshape(-1, 7 * 7 * 512)))
    _set(t, "fc1/bias", sd["classifier.0.bias"])
    _linear(t, "fc2", sd, "classifier.3")
    return t


# ---------------------------------------------------------------------------
# DINO ViT (facebookresearch/dino naming)
# ---------------------------------------------------------------------------

def convert_dino_vit(sd: StateDict, depth: int = 12) -> dict:
    t: dict = {}
    _set(t, "cls_token", sd["cls_token"].reshape(1, 1, -1))
    _set(t, "pos_embed", sd["pos_embed"])
    _conv(t, "patch_embed/proj", sd, "patch_embed.proj")
    for i in range(depth):
        src = f"blocks.{i}"
        dst = f"blocks_{i}"
        _layernorm(t, f"{dst}/norm1", sd, f"{src}.norm1")
        _linear(t, f"{dst}/qkv", sd, f"{src}.attn.qkv")
        _linear(t, f"{dst}/proj", sd, f"{src}.attn.proj")
        _layernorm(t, f"{dst}/norm2", sd, f"{src}.norm2")
        _linear(t, f"{dst}/fc1", sd, f"{src}.mlp.fc1")
        _linear(t, f"{dst}/fc2", sd, f"{src}.mlp.fc2")
    _layernorm(t, "norm", sd, "norm")
    return t


# ---------------------------------------------------------------------------
# XCiT (facebookresearch/xcit naming, DINO hub checkpoints
# dino_vits.py:413-487) -> models.xcit.XCiT
# ---------------------------------------------------------------------------

def convert_xcit(sd: StateDict) -> dict:
    """Depth / patch size / cls-attn count are inferred from the key set, so
    one converter serves all four dino_xcit_* checkpoints."""
    def layer_count(prefix: str) -> int:
        idx = [int(m.group(1)) for k in sd
               if (m := re.match(rf"{prefix}\.(\d+)\.", k))]
        if not idx:
            raise ValueError(
                f"not an XCiT state dict: no '{prefix}.N.*' keys "
                f"(got e.g. {sorted(sd)[:3]})")
        return 1 + max(idx)

    depth = layer_count("blocks")
    n_cls = layer_count("cls_attn_blocks")
    # /16 embeds through 4 conv stages (Sequential indices 0,2,4,6 with GELU
    # between), /8 through 3 (0,2,4)
    stages = [i for i in (0, 2, 4, 6) if f"patch_embed.proj.{i}.0.weight" in sd]
    t: dict = {}
    _set(t, "cls_token", sd["cls_token"].reshape(1, 1, -1))
    _conv(t, "pos_embeder/token_projection", sd, "pos_embeder.token_projection")
    for dst_i, src_i in enumerate(stages):
        _conv(t, f"patch_embed/conv{dst_i}", sd, f"patch_embed.proj.{src_i}.0")
        _batchnorm(t, f"patch_embed/bn{dst_i}", sd, f"patch_embed.proj.{src_i}.1")
    for i in range(depth):
        src, dst = f"blocks.{i}", f"blocks_{i}"
        for g in ("gamma1", "gamma2", "gamma3"):
            _set(t, f"{dst}/{g}", sd[f"{src}.{g}"])
        _layernorm(t, f"{dst}/norm1", sd, f"{src}.norm1")
        _set(t, f"{dst}/attn/temperature", sd[f"{src}.attn.temperature"])
        _linear(t, f"{dst}/attn/qkv", sd, f"{src}.attn.qkv")
        _linear(t, f"{dst}/attn/proj", sd, f"{src}.attn.proj")
        _layernorm(t, f"{dst}/norm3", sd, f"{src}.norm3")
        _conv(t, f"{dst}/local_mp/conv1", sd, f"{src}.local_mp.conv1")
        _batchnorm(t, f"{dst}/local_mp/bn", sd, f"{src}.local_mp.bn")
        _conv(t, f"{dst}/local_mp/conv2", sd, f"{src}.local_mp.conv2")
        _layernorm(t, f"{dst}/norm2", sd, f"{src}.norm2")
        _linear(t, f"{dst}/mlp/fc1", sd, f"{src}.mlp.fc1")
        _linear(t, f"{dst}/mlp/fc2", sd, f"{src}.mlp.fc2")
    for i in range(n_cls):
        src, dst = f"cls_attn_blocks.{i}", f"cls_attn_blocks_{i}"
        for g in ("gamma1", "gamma2"):
            _set(t, f"{dst}/{g}", sd[f"{src}.{g}"])
        _layernorm(t, f"{dst}/norm1", sd, f"{src}.norm1")
        _linear(t, f"{dst}/attn/qkv", sd, f"{src}.attn.qkv")
        _linear(t, f"{dst}/attn/proj", sd, f"{src}.attn.proj")
        _layernorm(t, f"{dst}/norm2", sd, f"{src}.norm2")
        _linear(t, f"{dst}/mlp/fc1", sd, f"{src}.mlp.fc1")
        _linear(t, f"{dst}/mlp/fc2", sd, f"{src}.mlp.fc2")
    _layernorm(t, "norm", sd, "norm")
    return t


# ---------------------------------------------------------------------------
# HF CLIPTextModel (transformers naming) -> models.clip_text.CLIPTextModel
# ---------------------------------------------------------------------------

def convert_clip_text(sd: StateDict, *, layers: int, heads: int) -> dict:
    p = "text_model." if any(k.startswith("text_model.") for k in sd) else ""
    t: dict = {}
    emb = sd[f"{p}embeddings.token_embedding.weight"]
    _set(t, "token_embedding/embedding", emb)
    _set(t, "position_embedding", sd[f"{p}embeddings.position_embedding.weight"])
    d = emb.shape[1]
    head_dim = d // heads
    for i in range(layers):
        src = f"{p}encoder.layers.{i}"
        dst = f"layers_{i}"
        _layernorm(t, f"{dst}/ln1", sd, f"{src}.layer_norm1")
        _layernorm(t, f"{dst}/ln2", sd, f"{src}.layer_norm2")
        # flax MultiHeadDotProductAttention: query/key/value kernels
        # [D, H, head_dim], out kernel [H, head_dim, D]
        for torch_name, flax_name in (("q_proj", "query"), ("k_proj", "key"),
                                      ("v_proj", "value")):
            w = linear_kernel(sd[f"{src}.self_attn.{torch_name}.weight"])
            b = sd[f"{src}.self_attn.{torch_name}.bias"]
            _set(t, f"{dst}/attn/{flax_name}/kernel", w.reshape(d, heads, head_dim))
            _set(t, f"{dst}/attn/{flax_name}/bias", b.reshape(heads, head_dim))
        wo = sd[f"{src}.self_attn.out_proj.weight"]  # [D, D] = [out, in]
        _set(t, f"{dst}/attn/out/kernel",
             linear_kernel(wo).reshape(heads, head_dim, d))
        _set(t, f"{dst}/attn/out/bias", sd[f"{src}.self_attn.out_proj.bias"])
        _linear(t, f"{dst}/fc1", sd, f"{src}.mlp.fc1")
        _linear(t, f"{dst}/fc2", sd, f"{src}.mlp.fc2")
    _layernorm(t, "final_layer_norm", sd, f"{p}final_layer_norm")
    return t


# ---------------------------------------------------------------------------
# CLIP image tower (+ full CLIP) -> models.clip_image.CLIPImageTower / scorer
# ---------------------------------------------------------------------------

def convert_clip_image(sd: StateDict, *, layers: int = 12) -> dict:
    """OpenAI CLIP (`visual.*`, fused in_proj) or transformers CLIPVisionModel
    (`vision_model.*`, split q/k/v) -> CLIPImageTower params. Reference role:
    the CLIP retrieval backbone + alignment score (diff_retrieval.py:268-275,
    utils_ret.py:1045-1066)."""
    t: dict = {}
    if any(k.startswith("visual.") for k in sd):
        _set(t, "patch_embed/kernel", conv_kernel(sd["visual.conv1.weight"]))
        _set(t, "class_embedding", sd["visual.class_embedding"])
        _set(t, "pos_embed", sd["visual.positional_embedding"][None])
        _layernorm(t, "ln_pre", sd, "visual.ln_pre")
        for i in range(layers):
            src = f"visual.transformer.resblocks.{i}"
            dst = f"blocks_{i}"
            _layernorm(t, f"{dst}/norm1", sd, f"{src}.ln_1")
            _set(t, f"{dst}/qkv/kernel",
                 linear_kernel(sd[f"{src}.attn.in_proj_weight"]))
            _set(t, f"{dst}/qkv/bias", sd[f"{src}.attn.in_proj_bias"])
            _linear(t, f"{dst}/proj", sd, f"{src}.attn.out_proj")
            _layernorm(t, f"{dst}/norm2", sd, f"{src}.ln_2")
            _linear(t, f"{dst}/fc1", sd, f"{src}.mlp.c_fc")
            _linear(t, f"{dst}/fc2", sd, f"{src}.mlp.c_proj")
        _layernorm(t, "ln_post", sd, "visual.ln_post")
        _set(t, "proj", sd["visual.proj"])        # stored [width, embed_dim]
        return t

    p = "vision_model."
    if not any(k.startswith(p) for k in sd):
        raise KeyError("state dict is neither OpenAI CLIP (visual.*) nor "
                       "transformers CLIPVisionModel (vision_model.*)")
    _set(t, "patch_embed/kernel",
         conv_kernel(sd[f"{p}embeddings.patch_embedding.weight"]))
    _set(t, "class_embedding", sd[f"{p}embeddings.class_embedding"].reshape(-1))
    _set(t, "pos_embed", sd[f"{p}embeddings.position_embedding.weight"][None])
    # transformers ships the typo'd name "pre_layrnorm"; accept both spellings
    pre = f"{p}pre_layrnorm" if f"{p}pre_layrnorm.weight" in sd else f"{p}pre_layernorm"
    _layernorm(t, "ln_pre", sd, pre)
    for i in range(layers):
        src = f"{p}encoder.layers.{i}"
        dst = f"blocks_{i}"
        _layernorm(t, f"{dst}/norm1", sd, f"{src}.layer_norm1")
        qkv_w = np.concatenate([sd[f"{src}.self_attn.{n}_proj.weight"]
                                for n in ("q", "k", "v")], axis=0)
        qkv_b = np.concatenate([sd[f"{src}.self_attn.{n}_proj.bias"]
                                for n in ("q", "k", "v")], axis=0)
        _set(t, f"{dst}/qkv/kernel", linear_kernel(qkv_w))
        _set(t, f"{dst}/qkv/bias", qkv_b)
        _linear(t, f"{dst}/proj", sd, f"{src}.self_attn.out_proj")
        _layernorm(t, f"{dst}/norm2", sd, f"{src}.layer_norm2")
        _linear(t, f"{dst}/fc1", sd, f"{src}.mlp.fc1")
        _linear(t, f"{dst}/fc2", sd, f"{src}.mlp.fc2")
    _layernorm(t, "ln_post", sd, f"{p}post_layernorm")
    if "visual_projection.weight" in sd:
        _set(t, "proj", linear_kernel(sd["visual_projection.weight"]))
    return t


def convert_openai_clip_text(sd: StateDict, *, layers: int = 12,
                             heads: int = 8) -> dict:
    """OpenAI CLIP text tower (`transformer.resblocks.*`, fused in_proj) ->
    models.clip_text.CLIPTextModel params."""
    t: dict = {}
    emb = sd["token_embedding.weight"]
    d = emb.shape[1]
    head_dim = d // heads
    _set(t, "token_embedding/embedding", emb)
    _set(t, "position_embedding", sd["positional_embedding"])
    for i in range(layers):
        src = f"transformer.resblocks.{i}"
        dst = f"layers_{i}"
        _layernorm(t, f"{dst}/ln1", sd, f"{src}.ln_1")
        _layernorm(t, f"{dst}/ln2", sd, f"{src}.ln_2")
        w = sd[f"{src}.attn.in_proj_weight"]      # [3D, D] rows q;k;v
        b = sd[f"{src}.attn.in_proj_bias"]
        for j, flax_name in enumerate(("query", "key", "value")):
            _set(t, f"{dst}/attn/{flax_name}/kernel",
                 linear_kernel(w[j * d:(j + 1) * d]).reshape(d, heads, head_dim))
            _set(t, f"{dst}/attn/{flax_name}/bias",
                 b[j * d:(j + 1) * d].reshape(heads, head_dim))
        _set(t, f"{dst}/attn/out/kernel",
             linear_kernel(sd[f"{src}.attn.out_proj.weight"]).reshape(
                 heads, head_dim, d))
        _set(t, f"{dst}/attn/out/bias", sd[f"{src}.attn.out_proj.bias"])
        _linear(t, f"{dst}/fc1", sd, f"{src}.mlp.c_fc")
        _linear(t, f"{dst}/fc2", sd, f"{src}.mlp.c_proj")
    _layernorm(t, "final_layer_norm", sd, "ln_final")
    return t


def convert_openai_clip(sd: StateDict, *, image_layers: int = 12,
                        text_layers: int = 12, text_heads: int = 8) -> dict:
    """Full OpenAI CLIP archive -> CLIPScorer params
    ({image, text, text_projection}). The image tower's fused qkv copies
    head-agnostically (our ViTBlock splits at apply time); only the text
    tower's flax attention needs the head count."""
    return {
        "image": convert_clip_image(sd, layers=image_layers),
        "text": convert_openai_clip_text(sd, layers=text_layers,
                                         heads=text_heads),
        "text_projection": np.asarray(sd["text_projection"]),  # [D, embed]
    }


# ---------------------------------------------------------------------------
# diffusers UNet2DConditionModel -> models.unet2d.UNet2DCondition
# ---------------------------------------------------------------------------

def _resnet_block(t: dict, dst: str, sd: StateDict, src: str) -> None:
    _groupnorm(t, f"{dst}/norm1", sd, f"{src}.norm1")
    _conv(t, f"{dst}/conv1", sd, f"{src}.conv1")
    if f"{src}.time_emb_proj.weight" in sd:
        _linear(t, f"{dst}/time_emb_proj", sd, f"{src}.time_emb_proj")
    _groupnorm(t, f"{dst}/norm2", sd, f"{src}.norm2")
    _conv(t, f"{dst}/conv2", sd, f"{src}.conv2")
    if f"{src}.conv_shortcut.weight" in sd:
        _conv(t, f"{dst}/conv_shortcut", sd, f"{src}.conv_shortcut")


def _transformer2d(t: dict, dst: str, sd: StateDict, src: str,
                   num_layers: int) -> None:
    _groupnorm(t, f"{dst}/norm", sd, f"{src}.norm")
    for proj in ("proj_in", "proj_out"):
        # SD-2.x projects with linears, SD-1.x with 1x1 convs (4-D weight)
        if sd[f"{src}.{proj}.weight"].ndim == 4:
            _conv(t, f"{dst}/{proj}", sd, f"{src}.{proj}")
        else:
            _linear(t, f"{dst}/{proj}", sd, f"{src}.{proj}")
    for k in range(num_layers):
        bsrc = f"{src}.transformer_blocks.{k}"
        bdst = f"{dst}/blocks_{k}"
        for attn in ("attn1", "attn2"):
            for qkv in ("to_q", "to_k", "to_v"):
                _linear(t, f"{bdst}/{attn}/{qkv}", sd, f"{bsrc}.{attn}.{qkv}")
            _linear(t, f"{bdst}/{attn}/to_out", sd, f"{bsrc}.{attn}.to_out.0")
        _linear(t, f"{bdst}/ff/proj_in", sd, f"{bsrc}.ff.net.0.proj")
        _linear(t, f"{bdst}/ff/proj_out", sd, f"{bsrc}.ff.net.2")
        for n in ("norm1", "norm2", "norm3"):
            _layernorm(t, f"{bdst}/{n}", sd, f"{bsrc}.{n}")


def convert_unet(sd: StateDict, *, block_out_channels=(320, 640, 1280, 1280),
                 layers_per_block: int = 2, transformer_layers: int = 1) -> dict:
    t: dict = {}
    n = len(block_out_channels)
    _conv(t, "conv_in", sd, "conv_in")
    _linear(t, "time_embedding/linear_1", sd, "time_embedding.linear_1")
    _linear(t, "time_embedding/linear_2", sd, "time_embedding.linear_2")
    for i in range(n):
        has_attn = i < n - 1
        for j in range(layers_per_block):
            _resnet_block(t, f"down_{i}_res_{j}", sd,
                          f"down_blocks.{i}.resnets.{j}")
            if has_attn:
                _transformer2d(t, f"down_{i}_attn_{j}", sd,
                               f"down_blocks.{i}.attentions.{j}",
                               transformer_layers)
        if f"down_blocks.{i}.downsamplers.0.conv.weight" in sd:
            _conv(t, f"down_{i}_downsample/conv", sd,
                  f"down_blocks.{i}.downsamplers.0.conv")
    _resnet_block(t, "mid_res_0", sd, "mid_block.resnets.0")
    _resnet_block(t, "mid_res_1", sd, "mid_block.resnets.1")
    _transformer2d(t, "mid_attn", sd, "mid_block.attentions.0",
                   transformer_layers)
    for i in range(n):  # diffusers up_blocks.i processes bottom-up
        block_idx = n - 1 - i
        has_attn = i > 0
        for j in range(layers_per_block + 1):
            _resnet_block(t, f"up_{block_idx}_res_{j}", sd,
                          f"up_blocks.{i}.resnets.{j}")
            if has_attn:
                _transformer2d(t, f"up_{block_idx}_attn_{j}", sd,
                               f"up_blocks.{i}.attentions.{j}",
                               transformer_layers)
        if f"up_blocks.{i}.upsamplers.0.conv.weight" in sd:
            _conv(t, f"up_{block_idx}_upsample/conv", sd,
                  f"up_blocks.{i}.upsamplers.0.conv")
    _groupnorm(t, "conv_norm_out", sd, "conv_norm_out")
    _conv(t, "conv_out", sd, "conv_out")
    return t


# ---------------------------------------------------------------------------
# diffusers AutoencoderKL -> models.vae.AutoencoderKL
# ---------------------------------------------------------------------------

def _vae_attn(t: dict, dst: str, sd: StateDict, src: str) -> None:
    _groupnorm(t, f"{dst}/group_norm", sd, f"{src}.group_norm")
    for name in ("to_q", "to_k", "to_v"):
        _linear(t, f"{dst}/{name}", sd, f"{src}.{name}")
    _linear(t, f"{dst}/to_out", sd, f"{src}.to_out.0")


_VAE_ATTN_RENAMES = {  # diffusers <=0.16 AttentionBlock -> >=0.17 Attention
    "query": "to_q", "key": "to_k", "value": "to_v", "proj_attn": "to_out.0"}


def normalize_vae_attn_names(sd: StateDict) -> dict[str, Arr]:
    """On-hub SD VAE checkpoints (serialized by diffusers <=0.16, the era the
    reference pins — env.yaml:325 diffusers==0.14.0) name the mid-block
    attention query/key/value/proj_attn; later diffusers renamed these
    to_q/to_k/to_v/to_out.0. Map the old names so both load."""
    out = {}
    for k, v in sd.items():
        m = re.match(r"(.*\.attentions\.\d+)\.(query|key|value|proj_attn)\.(.+)", k)
        if m:
            k = f"{m.group(1)}.{_VAE_ATTN_RENAMES[m.group(2)]}.{m.group(3)}"
        out[k] = v
    return out


def convert_vae(sd: StateDict, *, block_out_channels=(128, 256, 512, 512),
                layers_per_block: int = 2) -> dict:
    sd = normalize_vae_attn_names(sd)
    t: dict = {}
    n = len(block_out_channels)
    enc, dec = "encoder", "decoder"
    _conv(t, f"{enc}/conv_in", sd, "encoder.conv_in")
    for i in range(n):
        for j in range(layers_per_block):
            _resnet_block(t, f"{enc}/down_{i}_res_{j}", sd,
                          f"encoder.down_blocks.{i}.resnets.{j}")
        if f"encoder.down_blocks.{i}.downsamplers.0.conv.weight" in sd:
            _conv(t, f"{enc}/down_{i}_downsample/conv", sd,
                  f"encoder.down_blocks.{i}.downsamplers.0.conv")
    _resnet_block(t, f"{enc}/mid_res_0", sd, "encoder.mid_block.resnets.0")
    _resnet_block(t, f"{enc}/mid_res_1", sd, "encoder.mid_block.resnets.1")
    _vae_attn(t, f"{enc}/mid_attn", sd, "encoder.mid_block.attentions.0")
    _groupnorm(t, f"{enc}/conv_norm_out", sd, "encoder.conv_norm_out")
    _conv(t, f"{enc}/conv_out", sd, "encoder.conv_out")
    _conv(t, f"{enc}/quant_conv", sd, "quant_conv")
    _conv(t, f"{dec}/post_quant_conv", sd, "post_quant_conv")
    _conv(t, f"{dec}/conv_in", sd, "decoder.conv_in")
    _resnet_block(t, f"{dec}/mid_res_0", sd, "decoder.mid_block.resnets.0")
    _resnet_block(t, f"{dec}/mid_res_1", sd, "decoder.mid_block.resnets.1")
    _vae_attn(t, f"{dec}/mid_attn", sd, "decoder.mid_block.attentions.0")
    for i in range(n):
        for j in range(layers_per_block + 1):
            _resnet_block(t, f"{dec}/up_{i}_res_{j}", sd,
                          f"decoder.up_blocks.{i}.resnets.{j}")
        if f"decoder.up_blocks.{i}.upsamplers.0.conv.weight" in sd:
            _conv(t, f"{dec}/up_{i}_upsample/conv", sd,
                  f"decoder.up_blocks.{i}.upsamplers.0.conv")
    _groupnorm(t, f"{dec}/conv_norm_out", sd, "decoder.conv_norm_out")
    _conv(t, f"{dec}/conv_out", sd, "decoder.conv_out")
    return t


# ---------------------------------------------------------------------------
# validation
# ---------------------------------------------------------------------------

def check_converted(params_expected, params_converted, *, path: str = "") -> list[str]:
    """Structural diff: (path, why) strings for every mismatch — run after any
    conversion; empty list = tree and shapes line up exactly."""
    problems: list[str] = []
    exp_is_dict = isinstance(params_expected, dict)
    conv_is_dict = isinstance(params_converted, dict)
    if exp_is_dict != conv_is_dict:
        return [f"{path}: dict/leaf mismatch"]
    if exp_is_dict:
        for k in sorted(set(params_expected) | set(params_converted)):
            if k not in params_expected:
                problems.append(f"{path}/{k}: unexpected in converted")
            elif k not in params_converted:
                problems.append(f"{path}/{k}: missing from converted")
            else:
                problems += check_converted(params_expected[k],
                                            params_converted[k],
                                            path=f"{path}/{k}")
        return problems
    exp_shape = tuple(np.shape(params_expected))
    conv_shape = tuple(np.shape(params_converted))
    if exp_shape != conv_shape:
        problems.append(f"{path}: shape {conv_shape} != expected {exp_shape}")
    return problems

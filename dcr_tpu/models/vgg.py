"""VGG16 fc2 features — the Improved Precision & Recall embedder.

Capability-equivalent of metrics/ipr.py:41's torchvision VGG16 (features up to
fc2, 4096-d). Frozen feature extractor; weights via models/convert.py.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

# torchvision vgg16 conv plan: number = out channels, "M" = 2x2 maxpool
VGG16_PLAN = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M")


class VGG16Features(nn.Module):
    """[B,224,224,3] in [0,1] -> fc2 activations [B, 4096]."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # torchvision ImageNet normalization
        mean = jnp.asarray([0.485, 0.456, 0.406], x.dtype)
        std = jnp.asarray([0.229, 0.224, 0.225], x.dtype)
        x = (x - mean) / std
        conv_i = 0
        for item in VGG16_PLAN:
            if item == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(int(item), (3, 3), padding=((1, 1), (1, 1)),
                            dtype=self.dtype, name=f"conv_{conv_i}")(x)
                x = nn.relu(x)
                conv_i += 1
        x = x.reshape(x.shape[0], -1)  # [B, 7*7*512]
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc2")(x))
        return x


def init_vgg(key: jax.Array):
    model = VGG16Features()
    params = model.init(key, jnp.zeros((1, 224, 224, 3)))["params"]
    return model, params

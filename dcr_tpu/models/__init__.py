"""L3: Flax module zoo + diffusion schedule math."""

"""Diffusion noise schedules + samplers as pure jittable functions.

Capability parity with the scheduler surface the reference uses from diffusers:
DDPMScheduler.add_noise / get_velocity for training (diff_train.py:448,632,650)
and DPMSolverMultistepScheduler / default PNDM-style sampling for inference
(diff_inference.py:93). Implemented from the papers as stateless functions of a
precomputed :class:`NoiseSchedule`, so they compose with jit/scan/vmap — the
sampler loop lives in dcr_tpu.sampling as a ``lax.scan`` over these steps.

Math references: DDPM (Ho et al. 2020), DDIM (Song et al. 2020),
DPM-Solver++ (Lu et al. 2022).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class NoiseSchedule:
    """Precomputed diffusion coefficients, all shape [T] float32."""

    betas: jax.Array
    alphas_cumprod: jax.Array
    num_train_timesteps: int
    prediction_type: str = "epsilon"  # "epsilon" | "v_prediction" | "sample"

    @property
    def sqrt_alphas_cumprod(self) -> jax.Array:
        return jnp.sqrt(self.alphas_cumprod)

    @property
    def sqrt_one_minus_alphas_cumprod(self) -> jax.Array:
        return jnp.sqrt(1.0 - self.alphas_cumprod)


def make_schedule(num_train_timesteps: int = 1000, beta_schedule: str = "scaled_linear",
                  beta_start: float = 0.00085, beta_end: float = 0.012,
                  prediction_type: str = "epsilon") -> NoiseSchedule:
    if beta_schedule == "linear":
        betas = np.linspace(beta_start, beta_end, num_train_timesteps, dtype=np.float64)
    elif beta_schedule == "scaled_linear":
        # SD's schedule: linear in sqrt(beta)
        betas = np.linspace(beta_start ** 0.5, beta_end ** 0.5, num_train_timesteps,
                            dtype=np.float64) ** 2
    elif beta_schedule == "squaredcos_cap_v2":
        t = np.arange(num_train_timesteps, dtype=np.float64)

        def f(x):
            return np.cos((x / num_train_timesteps + 0.008) / 1.008 * np.pi / 2) ** 2

        betas = np.minimum(1.0 - f(t + 1) / f(t), 0.999)
    else:
        raise ValueError(f"unknown beta_schedule {beta_schedule!r}")
    alphas_cumprod = np.cumprod(1.0 - betas)
    return NoiseSchedule(
        betas=jnp.asarray(betas, jnp.float32),
        alphas_cumprod=jnp.asarray(alphas_cumprod, jnp.float32),
        num_train_timesteps=num_train_timesteps,
        prediction_type=prediction_type,
    )


def _gather(coeffs: jax.Array, t: jax.Array, ndim: int) -> jax.Array:
    """coeffs[t] broadcast against an ndim-rank batched tensor."""
    c = coeffs[t]
    return c.reshape(c.shape + (1,) * (ndim - c.ndim))


def _bcast(v: jax.Array, ndim: int) -> jax.Array:
    """Broadcast a scalar or [B] per-timestep value against an ndim-rank tensor."""
    v = jnp.asarray(v)
    return v.reshape(v.shape + (1,) * (ndim - v.ndim))


def _acp_prev(sched: NoiseSchedule, prev_t: jax.Array, ndim: int) -> jax.Array:
    """alphas_cumprod[prev_t] with prev_t=-1 meaning "fully denoised" (acp=1)."""
    prev_t = jnp.asarray(prev_t)
    acp = sched.alphas_cumprod[jnp.maximum(prev_t, 0)]
    acp = jnp.where(prev_t >= 0, acp, 1.0)
    return _bcast(acp, ndim)


def add_noise(sched: NoiseSchedule, x0: jax.Array, noise: jax.Array,
              t: jax.Array) -> jax.Array:
    """q(x_t | x_0): forward diffusion (reference uses DDPMScheduler.add_noise,
    diff_train.py:632)."""
    a = _gather(sched.sqrt_alphas_cumprod, t, x0.ndim)
    s = _gather(sched.sqrt_one_minus_alphas_cumprod, t, x0.ndim)
    return a * x0.astype(jnp.float32) + s * noise.astype(jnp.float32)


def get_velocity(sched: NoiseSchedule, x0: jax.Array, noise: jax.Array,
                 t: jax.Array) -> jax.Array:
    """v-prediction target (reference diff_train.py:650)."""
    a = _gather(sched.sqrt_alphas_cumprod, t, x0.ndim)
    s = _gather(sched.sqrt_one_minus_alphas_cumprod, t, x0.ndim)
    return a * noise.astype(jnp.float32) - s * x0.astype(jnp.float32)


def training_target(sched: NoiseSchedule, x0: jax.Array, noise: jax.Array,
                    t: jax.Array) -> jax.Array:
    if sched.prediction_type == "epsilon":
        return noise
    if sched.prediction_type == "v_prediction":
        return get_velocity(sched, x0, noise, t)
    if sched.prediction_type == "sample":
        return x0
    raise ValueError(f"unknown prediction_type {sched.prediction_type!r}")


def pred_to_x0_eps(sched: NoiseSchedule, model_out: jax.Array, x_t: jax.Array,
                   t: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Convert the model's output under its prediction_type to (x0_hat, eps_hat)."""
    a = _gather(sched.sqrt_alphas_cumprod, t, x_t.ndim)
    s = _gather(sched.sqrt_one_minus_alphas_cumprod, t, x_t.ndim)
    if sched.prediction_type == "epsilon":
        eps = model_out
        x0 = (x_t - s * eps) / a
    elif sched.prediction_type == "v_prediction":
        x0 = a * x_t - s * model_out
        eps = a * model_out + s * x_t
    elif sched.prediction_type == "sample":
        x0 = model_out
        eps = (x_t - a * x0) / s
    else:
        raise ValueError(sched.prediction_type)
    return x0, eps


# ---------------------------------------------------------------------------
# Inference-time timestep grids
# ---------------------------------------------------------------------------

def inference_timesteps(sched: NoiseSchedule, num_inference_steps: int,
                        spacing: str = "leading", steps_offset: int = 1) -> jax.Array:
    """Descending timestep grid [num_inference_steps].

    Mirrors diffusers' ``set_timesteps`` grids so sampled trajectories are
    comparable to the reference pipeline (diff_inference.py:93):

    - ``"leading"``: DDIM/PNDM-family. ``steps_offset`` (1 in SD's shipped
      scheduler configs) shifts the whole grid up by one training timestep;
      clipped to num_train_timesteps-1.
    - ``"linspace"``: DPMSolverMultistep's default — n+1 evenly spaced points
      over [0, T-1], reversed, last dropped. ``steps_offset`` is unused here,
      matching diffusers.
    """
    T = sched.num_train_timesteps
    if num_inference_steps > T:
        raise ValueError(
            f"num_inference_steps={num_inference_steps} exceeds "
            f"num_train_timesteps={T}")
    if spacing == "leading":
        step = T // num_inference_steps
        ts = (np.arange(num_inference_steps) * step).round()[::-1].copy()
        ts = np.minimum(ts + steps_offset, T - 1)
    elif spacing == "linspace":
        ts = np.linspace(0, T - 1, num_inference_steps + 1).round()[::-1][:-1].copy()
    else:
        raise ValueError(f"unknown timestep spacing {spacing!r}")
    return jnp.asarray(ts.astype(np.int32))


# ---------------------------------------------------------------------------
# DDPM ancestral step
# ---------------------------------------------------------------------------

def ddpm_step(sched: NoiseSchedule, model_out: jax.Array, x_t: jax.Array,
              t: jax.Array, prev_t: jax.Array, key: jax.Array) -> jax.Array:
    x0, eps = pred_to_x0_eps(sched, model_out, x_t, t)
    x0 = jnp.clip(x0, -1000.0, 1000.0)
    acp = _gather(sched.alphas_cumprod, t, x_t.ndim)
    acp_prev = _acp_prev(sched, prev_t, x_t.ndim)
    alpha_t = acp / acp_prev
    beta_t = 1.0 - alpha_t
    # posterior mean coefficients (Ho et al. eq. 7)
    coef_x0 = jnp.sqrt(acp_prev) * beta_t / (1.0 - acp)
    coef_xt = jnp.sqrt(alpha_t) * (1.0 - acp_prev) / (1.0 - acp)
    mean = coef_x0 * x0 + coef_xt * x_t
    var = beta_t * (1.0 - acp_prev) / (1.0 - acp)
    noise = jax.random.normal(key, x_t.shape, x_t.dtype)
    add_noise_mask = _bcast(jnp.asarray(prev_t) >= 0, x_t.ndim)
    return jnp.where(add_noise_mask,
                     mean + jnp.sqrt(jnp.maximum(var, 1e-20)) * noise, mean)


# ---------------------------------------------------------------------------
# DDIM step (eta=0, deterministic)
# ---------------------------------------------------------------------------

def ddim_step(sched: NoiseSchedule, model_out: jax.Array, x_t: jax.Array,
              t: jax.Array, prev_t: jax.Array) -> jax.Array:
    x0, eps = pred_to_x0_eps(sched, model_out, x_t, t)
    acp_prev = _acp_prev(sched, prev_t, x_t.ndim)
    return jnp.sqrt(acp_prev) * x0 + jnp.sqrt(1.0 - acp_prev) * eps


# ---------------------------------------------------------------------------
# DPM-Solver++ (2M multistep) — the reference's stock-SD sampler
# (diff_inference.py:93). Data-prediction formulation, order 2.
# ---------------------------------------------------------------------------

@flax.struct.dataclass
class DPMState:
    """Carried through the sampling scan (a pytree)."""

    prev_x0: jax.Array   # x0 prediction at the previous step
    prev_lambda: jax.Array
    step_index: jax.Array  # 0 at first step (first-order bootstrap)


def _lambda_of(sched: NoiseSchedule, t: jax.Array) -> jax.Array:
    acp = sched.alphas_cumprod[jnp.maximum(t, 0)]
    acp = jnp.where(t >= 0, acp, 1.0 - 1e-8)
    alpha = jnp.sqrt(acp)
    sigma = jnp.sqrt(1.0 - acp)
    return jnp.log(alpha) - jnp.log(jnp.maximum(sigma, 1e-20))


def dpmpp_2m_step(sched: NoiseSchedule, model_out: jax.Array, x_t: jax.Array,
                  t: jax.Array, prev_t: jax.Array, state: DPMState,
                  force_first_order: jax.Array | bool = False) -> tuple[jax.Array, DPMState]:
    """One DPM-Solver++(2M) update x_t -> x_{prev_t}; t/prev_t scalar or [B].

    First call (state.step_index == 0) falls back to the first-order (DDIM-like)
    update; later calls use the 2nd-order multistep correction. With batched t,
    initialize the state via ``dpm_init_state(x.shape, batch_shape=t.shape)``.

    ``force_first_order`` mirrors diffusers' ``lower_order_final``: the caller
    sets it on the final step of short (<15-step) trajectories for stability.
    """
    nd = x_t.ndim
    x0, _eps = pred_to_x0_eps(sched, model_out, x_t, t)

    lam_t = _lambda_of(sched, t)
    lam_s = _lambda_of(sched, prev_t)
    h = lam_s - lam_t

    prev_t = jnp.asarray(prev_t)
    acp_s = jnp.where(prev_t >= 0, sched.alphas_cumprod[jnp.maximum(prev_t, 0)], 1.0)
    alpha_s = jnp.sqrt(acp_s)
    sigma_s = jnp.sqrt(1.0 - acp_s)
    acp_t = sched.alphas_cumprod[t]
    sigma_t = jnp.sqrt(1.0 - acp_t)

    ratio = _bcast(sigma_s / jnp.maximum(sigma_t, 1e-20), nd)
    phi = _bcast(jnp.expm1(-h), nd)

    # 2nd-order combination of current and previous x0 predictions
    h_last = lam_t - state.prev_lambda
    r = h_last / jnp.where(h == 0, 1e-20, h)
    inv2r = _bcast(1.0 / (2.0 * jnp.maximum(r, 1e-20)), nd)
    use_second = jnp.logical_and(state.step_index > 0,
                                 jnp.logical_not(force_first_order))
    d = jnp.where(use_second, (1.0 + inv2r) * x0 - inv2r * state.prev_x0, x0)

    x_prev = ratio * x_t - _bcast(alpha_s, nd) * phi * d
    new_state = DPMState(prev_x0=x0,
                         prev_lambda=jnp.broadcast_to(lam_t, state.prev_lambda.shape),
                         step_index=state.step_index + 1)
    return x_prev, new_state


def dpm_init_state(shape: tuple[int, ...], dtype=jnp.float32,
                   batch_shape: tuple[int, ...] = ()) -> DPMState:
    """batch_shape must match t's shape when stepping with batched timesteps."""
    return DPMState(prev_x0=jnp.zeros(shape, dtype),
                    prev_lambda=jnp.zeros(batch_shape),
                    step_index=jnp.zeros((), jnp.int32))

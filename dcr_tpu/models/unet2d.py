"""UNet2DCondition — the flagship denoiser (SD-2.1 architecture), TPU-native Flax.

Capability-equivalent of the diffusers UNet2DConditionModel the reference
finetunes (diff_train.py:386-408: loaded from checkpoint or built from a
unet_config.json for --unet_from_scratch). NHWC, bf16-compute friendly, with
every attention going through dcr_tpu.ops (Pallas flash on TPU).

Structure (SD-2.x): conv_in → [CrossAttnDown ×(n-1), Down] → mid(Res, T2D, Res)
→ [Up, CrossAttnUp ×(n-1)] with skip concats → GN → silu → conv_out.
Timesteps enter through a sinusoidal embedding + MLP added in every ResnetBlock.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from dcr_tpu.core.config import ModelConfig
from dcr_tpu.models import layers as L


def attn_dims(cfg: ModelConfig, ch: int) -> tuple[int, int]:
    """(num_heads, head_dim) for a block of width ch. SD-2.x fixes head_dim
    (64) and varies the count; SD-1.x fixes the count (8) and varies the dim."""
    if cfg.attention_num_heads:
        return cfg.attention_num_heads, ch // cfg.attention_num_heads
    return ch // cfg.attention_head_dim, cfg.attention_head_dim


class UNet2DCondition(nn.Module):
    config: ModelConfig
    dtype: jnp.dtype = jnp.float32
    # attach a mesh with a seq axis >1 to enable ring-attention sequence
    # parallelism in the spatial self-attentions (config.seq_parallel_min_seq)
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(self, sample: jax.Array, timesteps: jax.Array,
                 encoder_hidden_states: jax.Array,
                 deterministic: bool = True) -> jax.Array:
        """sample: [B, H, W, C_latent]; timesteps: [B] int; context: [B, S, D_txt]."""
        cfg = self.config
        dtype = self.dtype
        block_out = cfg.block_out_channels
        n_blocks = len(block_out)
        groups = cfg.norm_num_groups

        def transformer(ch: int, name: str) -> L.Transformer2D:
            heads, head_dim = attn_dims(cfg, ch)
            return L.Transformer2D(
                heads, head_dim, num_layers=cfg.transformer_layers,
                num_groups=groups, use_flash=cfg.flash_attention,
                use_linear_projection=cfg.use_linear_projection, dtype=dtype,
                mesh=self.mesh,
                seq_parallel_min_seq=cfg.seq_parallel_min_seq,
                seq_parallel_mode=cfg.seq_parallel_mode, name=name)

        # --- time embedding
        t_emb = L.timestep_embedding(timesteps, block_out[0])
        temb = L.TimestepEmbedding(block_out[0] * 4, dtype=dtype,
                                   name="time_embedding")(t_emb.astype(dtype))

        context = encoder_hidden_states.astype(dtype)
        sample = sample.astype(dtype)

        # --- down path
        h = nn.Conv(block_out[0], (3, 3), padding=((1, 1), (1, 1)), dtype=dtype,
                    name="conv_in")(sample)
        skips = [h]
        for i, ch in enumerate(block_out):
            is_final = i == n_blocks - 1
            for j in range(cfg.layers_per_block):
                h = L.ResnetBlock2D(ch, num_groups=groups, dtype=dtype,
                                    name=f"down_{i}_res_{j}")(h, temb, deterministic)
                if not is_final:  # cross-attn blocks everywhere but the bottom
                    h = transformer(ch, f"down_{i}_attn_{j}")(h, context)
                skips.append(h)
            if not is_final:
                h = L.Downsample2D(ch, dtype=dtype, name=f"down_{i}_downsample")(h)
                skips.append(h)

        # --- mid
        mid_ch = block_out[-1]
        h = L.ResnetBlock2D(mid_ch, num_groups=groups, dtype=dtype,
                            name="mid_res_0")(h, temb, deterministic)
        h = transformer(mid_ch, "mid_attn")(h, context)
        h = L.ResnetBlock2D(mid_ch, num_groups=groups, dtype=dtype,
                            name="mid_res_1")(h, temb, deterministic)

        # --- up path (mirror, consuming skips)
        for i, ch in enumerate(reversed(block_out)):
            block_idx = n_blocks - 1 - i
            is_first = i == 0  # bottom of the U: no cross-attn (mirrors DownBlock2D)
            for j in range(cfg.layers_per_block + 1):
                skip = skips.pop()
                h = jnp.concatenate([h, skip], axis=-1)
                h = L.ResnetBlock2D(ch, num_groups=groups, dtype=dtype,
                                    name=f"up_{block_idx}_res_{j}")(h, temb, deterministic)
                if not is_first:
                    h = transformer(ch, f"up_{block_idx}_attn_{j}")(h, context)
            if block_idx > 0:
                h = L.Upsample2D(ch, dtype=dtype, name=f"up_{block_idx}_upsample")(h)

        # --- out
        h = L.GroupNorm(groups, name="conv_norm_out")(h)
        h = nn.silu(h)
        h = nn.Conv(cfg.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=dtype, name="conv_out")(h)
        return h.astype(jnp.float32)


def init_unet(cfg: ModelConfig, key: jax.Array, dtype=jnp.float32, mesh=None,
              model: "UNet2DCondition | None" = None):
    """Initialize params with tiny dummy shapes (shape-polymorphic in H/W).
    `mesh` (seq axis >1) turns on ring-attention sequence parallelism; init
    itself always runs the single-chip path (batch-1 dummy shapes never pass
    the divisibility gate). Pass `model` to init a prebuilt module
    (trainer.build_modules) instead of constructing a second one."""
    model = model if model is not None else UNet2DCondition(cfg, dtype=dtype, mesh=mesh)
    sample = jnp.zeros((1, cfg.sample_size, cfg.sample_size, cfg.in_channels))
    t = jnp.zeros((1,), jnp.int32)
    ctx = jnp.zeros((1, cfg.text_max_length, cfg.cross_attention_dim))
    params = model.init(key, sample, t, ctx)["params"]
    return model, params


def unet_param_count(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))

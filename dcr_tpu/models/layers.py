"""Shared Flax building blocks for the diffusion model zoo.

NHWC layout throughout (TPU-native; XLA tiles convs onto the MXU best with
features-last). The reference consumes these blocks from HF diffusers
(UNet2DConditionModel etc., diff_train.py:370-408) — here they are first-party.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from dcr_tpu.ops.attention import dot_product_attention


def timestep_embedding(t: jax.Array, dim: int, max_period: float = 10000.0,
                       flip_sin_to_cos: bool = True,
                       downscale_freq_shift: float = 0.0) -> jax.Array:
    """Sinusoidal timestep embedding [B] -> [B, dim] (Transformer/DDPM style)."""
    half = dim // 2
    freqs = jnp.exp(
        -jnp.log(max_period) * jnp.arange(half, dtype=jnp.float32)
        / (half - downscale_freq_shift)
    )
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    sin, cos = jnp.sin(args), jnp.cos(args)
    emb = jnp.concatenate([cos, sin] if flip_sin_to_cos else [sin, cos], axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


class TimestepEmbedding(nn.Module):
    """2-layer MLP lifting the sinusoidal embedding to the UNet's time channels."""

    dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, emb: jax.Array) -> jax.Array:
        emb = nn.Dense(self.dim, dtype=self.dtype, name="linear_1")(emb)
        emb = nn.silu(emb)
        emb = nn.Dense(self.dim, dtype=self.dtype, name="linear_2")(emb)
        return emb


class GroupNorm(nn.Module):
    """GroupNorm computing statistics in f32 always (the point of this wrapper);
    output is cast back to the input's compute dtype."""

    num_groups: int = 32
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        orig_dtype = x.dtype
        x = nn.GroupNorm(num_groups=self.num_groups, epsilon=self.epsilon,
                         dtype=jnp.float32, param_dtype=jnp.float32)(x.astype(jnp.float32))
        return x.astype(orig_dtype)


class ResnetBlock2D(nn.Module):
    """norm→silu→conv→(+time)→norm→silu→conv with learned/1x1 skip."""

    out_channels: int
    num_groups: int = 32
    epsilon: float = 1e-5
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, temb: Optional[jax.Array] = None,
                 deterministic: bool = True) -> jax.Array:
        residual = x
        h = GroupNorm(self.num_groups, self.epsilon, name="norm1")(x)
        h = nn.silu(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv1")(h)
        if temb is not None:
            temb_proj = nn.Dense(self.out_channels, dtype=self.dtype,
                                 name="time_emb_proj")(nn.silu(temb))
            h = h + temb_proj[:, None, None, :]
        h = GroupNorm(self.num_groups, self.epsilon, name="norm2")(h)
        h = nn.silu(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=deterministic)(h)
        h = nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                    dtype=self.dtype, name="conv2")(h)
        if residual.shape[-1] != self.out_channels:
            residual = nn.Conv(self.out_channels, (1, 1), dtype=self.dtype,
                               name="conv_shortcut")(residual)
        return h + residual


class CrossAttention(nn.Module):
    """Multi-head attention; self-attention when context is None.

    When a mesh with a seq axis >1 is attached and the (self-attention)
    sequence reaches seq_parallel_min_seq, dispatches to exact sequence/
    context parallelism over the mesh's `seq` axis — the long-context path
    (SURVEY §5.7; reference's only analogue is single-GPU xformers,
    diff_train.py:578). Two strategies, selected by seq_parallel_mode:

    - "ring": K/V shards rotate via ppermute, online-softmax merge
      (ops/ring_attention.py). No head-count constraint.
    - "ulysses": one all_to_all re-shards seq->heads, full-sequence
      attention per head group (riding the Pallas flash kernel on TPU),
      all_to_all back (ops/ulysses_attention.py). Needs heads % seq == 0;
      falls back to ring when they don't divide."""

    num_heads: int
    head_dim: int
    out_dim: int
    use_flash: bool = True
    dtype: jnp.dtype = jnp.float32
    mesh: Optional[jax.sharding.Mesh] = None
    seq_parallel_min_seq: int = 4096
    seq_parallel_mode: str = "ring"

    def _seq_n(self) -> int:
        from dcr_tpu.parallel.mesh import SEQ_AXIS

        return dict(self.mesh.shape).get(SEQ_AXIS, 1) if self.mesh else 1

    def _ring_ok(self, b: int, sq: int, is_self: bool) -> bool:
        if not is_self or self.mesh is None:
            return False
        from dcr_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS

        shape = dict(self.mesh.shape)
        n_seq = self._seq_n()
        n_batch = shape.get(DATA_AXIS, 1) * shape.get(FSDP_AXIS, 1)
        return (n_seq > 1 and sq >= self.seq_parallel_min_seq
                and sq % n_seq == 0 and b % n_batch == 0)

    @nn.compact
    def __call__(self, x: jax.Array, context: Optional[jax.Array] = None) -> jax.Array:
        is_self = context is None
        context = x if context is None else context
        inner = self.num_heads * self.head_dim
        q = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_q")(x)
        k = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_k")(context)
        v = nn.Dense(inner, use_bias=False, dtype=self.dtype, name="to_v")(context)
        b, sq = q.shape[0], q.shape[1]
        sk = k.shape[1]
        q = q.reshape(b, sq, self.num_heads, self.head_dim)
        k = k.reshape(b, sk, self.num_heads, self.head_dim)
        v = v.reshape(b, sk, self.num_heads, self.head_dim)
        if self._ring_ok(b, sq, is_self):
            if (self.seq_parallel_mode == "ulysses"
                    and self.num_heads % self._seq_n() == 0):
                from dcr_tpu.ops.ulysses_attention import ulysses_self_attention

                out = ulysses_self_attention(q, k, v, self.mesh,
                                             use_flash=self.use_flash)
            else:
                from dcr_tpu.ops.ring_attention import ring_self_attention

                out = ring_self_attention(q, k, v, self.mesh)
        else:
            out = dot_product_attention(q, k, v, use_flash=self.use_flash)
        out = out.reshape(b, sq, inner)
        return nn.Dense(self.out_dim, dtype=self.dtype, name="to_out")(out)


class FeedForward(nn.Module):
    """GEGLU feed-forward (SD transformer blocks)."""

    dim: int
    mult: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        inner = self.dim * self.mult
        h = nn.Dense(inner * 2, dtype=self.dtype, name="proj_in")(x)
        h, gate = jnp.split(h, 2, axis=-1)
        h = h * nn.gelu(gate)
        return nn.Dense(self.dim, dtype=self.dtype, name="proj_out")(h)


class BasicTransformerBlock(nn.Module):
    """self-attn → cross-attn → ff, each pre-LayerNormed with residuals.
    Only the self-attention (attn1) is eligible for sequence parallelism —
    cross-attention's K/V is the 77-token text context."""

    dim: int
    num_heads: int
    head_dim: int
    use_flash: bool = True
    dtype: jnp.dtype = jnp.float32
    mesh: Optional[jax.sharding.Mesh] = None
    seq_parallel_min_seq: int = 4096
    seq_parallel_mode: str = "ring"

    @nn.compact
    def __call__(self, x: jax.Array, context: jax.Array) -> jax.Array:
        attn = CrossAttention(self.num_heads, self.head_dim, self.dim,
                              use_flash=self.use_flash, dtype=self.dtype,
                              mesh=self.mesh,
                              seq_parallel_min_seq=self.seq_parallel_min_seq,
                              seq_parallel_mode=self.seq_parallel_mode,
                              name="attn1")
        x = x + attn(nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm1")(x))
        xattn = CrossAttention(self.num_heads, self.head_dim, self.dim,
                               use_flash=self.use_flash, dtype=self.dtype, name="attn2")
        x = x + xattn(nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm2")(x), context)
        ff = FeedForward(self.dim, dtype=self.dtype, name="ff")
        x = x + ff(nn.LayerNorm(epsilon=1e-5, dtype=self.dtype, name="norm3")(x))
        return x


class Transformer2D(nn.Module):
    """Spatial transformer: GN → proj in → N blocks → proj out + residual.

    use_linear_projection selects the SD-2.x linear projections (default) or
    the SD-1.x 1x1 convs — same math, different weight shape and apply order
    (conv before the [B,HW,C] reshape), matching diffusers so checkpoints of
    both families convert losslessly."""

    num_heads: int
    head_dim: int
    num_layers: int = 1
    num_groups: int = 32
    use_flash: bool = True
    use_linear_projection: bool = True
    dtype: jnp.dtype = jnp.float32
    mesh: Optional[jax.sharding.Mesh] = None
    seq_parallel_min_seq: int = 4096
    seq_parallel_mode: str = "ring"

    @nn.compact
    def __call__(self, x: jax.Array, context: jax.Array) -> jax.Array:
        b, h, w, c = x.shape
        residual = x
        inner = self.num_heads * self.head_dim
        # diffusers Transformer2DModel norms with eps=1e-6 (unlike the 1e-5
        # resnet norms); mismatch silently drifts converted SD weights
        out = GroupNorm(self.num_groups, epsilon=1e-6, name="norm")(x)
        if self.use_linear_projection:
            out = out.reshape(b, h * w, c)
            out = nn.Dense(inner, dtype=self.dtype, name="proj_in")(out)
        else:
            out = nn.Conv(inner, (1, 1), dtype=self.dtype, name="proj_in")(out)
            out = out.reshape(b, h * w, inner)
        for i in range(self.num_layers):
            out = BasicTransformerBlock(inner, self.num_heads, self.head_dim,
                                        use_flash=self.use_flash, dtype=self.dtype,
                                        mesh=self.mesh,
                                        seq_parallel_min_seq=self.seq_parallel_min_seq,
                                        seq_parallel_mode=self.seq_parallel_mode,
                                        name=f"blocks_{i}")(out, context)
        if self.use_linear_projection:
            out = nn.Dense(c, dtype=self.dtype, name="proj_out")(out)
            out = out.reshape(b, h, w, c)
        else:
            out = nn.Conv(c, (1, 1), dtype=self.dtype,
                          name="proj_out")(out.reshape(b, h, w, inner))
        return out + residual


class Downsample2D(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.float32
    # diffusers' AutoencoderKL encoder downsamples with padding=0 plus an
    # asymmetric (0,1,0,1) pre-pad (right/bottom only); the UNet downsampler
    # uses symmetric padding=1. Both produce the same output shape for even
    # inputs but sample different taps, so pretrained VAE weights require the
    # asymmetric variant to reproduce reference activations.
    asymmetric_pad: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        pad = ((0, 1), (0, 1)) if self.asymmetric_pad else ((1, 1), (1, 1))
        return nn.Conv(self.out_channels, (3, 3), strides=(2, 2),
                       padding=pad, dtype=self.dtype, name="conv")(x)


class Upsample2D(nn.Module):
    out_channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, h, w, c = x.shape
        x = jax.image.resize(x, (b, h * 2, w * 2, c), method="nearest")
        return nn.Conv(self.out_channels, (3, 3), padding=((1, 1), (1, 1)),
                       dtype=self.dtype, name="conv")(x)


class AttentionBlock2D(nn.Module):
    """Single-head (or multi-head) spatial self-attention used in VAE mid blocks."""

    num_heads: int = 1
    num_groups: int = 32
    epsilon: float = 1e-6
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, h, w, c = x.shape
        residual = x
        out = GroupNorm(self.num_groups, self.epsilon, name="group_norm")(x).reshape(b, h * w, c)
        head_dim = c // self.num_heads
        q = nn.Dense(c, dtype=self.dtype, name="to_q")(out)
        k = nn.Dense(c, dtype=self.dtype, name="to_k")(out)
        v = nn.Dense(c, dtype=self.dtype, name="to_v")(out)
        q = q.reshape(b, h * w, self.num_heads, head_dim)
        k = k.reshape(b, h * w, self.num_heads, head_dim)
        v = v.reshape(b, h * w, self.num_heads, head_dim)
        out = dot_product_attention(q, k, v, use_flash=False).reshape(b, h * w, c)
        out = nn.Dense(c, dtype=self.dtype, name="to_out")(out)
        return out.reshape(b, h, w, c) + residual

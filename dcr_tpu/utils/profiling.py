"""Profiling + MFU telemetry.

The reference has no tracing at all (SURVEY.md §5.1 — its closest artifact is
MetricLogger's iter/data timing). On TPU this is cheap and first-class:
jax.profiler trace capture around any code region, a step timer, and
model-FLOPs-utilization accounting against the chip's peak.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np

# dense peak TFLOP/s (bf16) per chip by TPU generation; used for MFU.
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0,   # v5e
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,   # trillium
    "cpu": 1.0,
}


def chip_peak_tflops() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for name, peak in PEAK_TFLOPS.items():
        if name in kind:
            return peak
    return PEAK_TFLOPS["cpu"]


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler trace capture around a region; view with tensorboard."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def flops_of_jitted(jitted_fn, *args, **kwargs) -> float:
    """Per-device FLOPs of an already-jitted function from XLA's cost analysis
    (post-GSPMD-partitioning, so this is the per-chip share). 0 if unavailable."""
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # older jax returns per-device list
            analysis = analysis[0]
        return float(analysis.get("flops", 0.0))
    except Exception:
        return 0.0


def compiled_flops(fn, *args, **kwargs) -> Optional[float]:
    """FLOPs estimate of a function from XLA's cost analysis."""
    return flops_of_jitted(jax.jit(fn), *args, **kwargs) or None


@dataclass
class StepTimer:
    """Steady-state step timing + images/sec + MFU.

    ``flops_per_step`` is the PER-DEVICE FLOP share (what
    :func:`flops_of_jitted` returns: post-GSPMD-partitioning cost analysis),
    so MFU is per-device achieved over per-device peak — dividing by
    ``device_count`` again, as an earlier revision did, under-reported MFU by
    exactly that factor. ``tflops_per_sec`` stays the per-device rate the
    flops input implies; ``tflops_per_sec_total`` scales it to the whole job.
    """

    flops_per_step: Optional[float] = None
    _t0: float = field(default_factory=time.perf_counter)
    _steps: int = 0
    _items: int = 0

    def tick(self, items: int = 0) -> None:
        self._steps += 1
        self._items += items

    def report(self, reset: bool = True) -> dict:
        dt = time.perf_counter() - self._t0
        steps = max(self._steps, 1)
        out = {
            "step_time_ms": 1e3 * dt / steps,
            "steps_per_sec": steps / dt if dt > 0 else float("inf"),
        }
        if self._items:
            out["items_per_sec"] = self._items / dt
        if self.flops_per_step:
            # per-device achieved TFLOP/s vs per-device peak: both sides of
            # the MFU ratio are per-chip, so device_count cancels
            achieved = self.flops_per_step * steps / dt / 1e12
            out["tflops_per_sec"] = achieved
            out["tflops_per_sec_total"] = achieved * jax.device_count()
            out["mfu"] = achieved / chip_peak_tflops()
        if reset:
            self._t0 = time.perf_counter()
            self._steps = self._items = 0
        return out

"""Profiling + MFU telemetry.

The reference has no tracing at all (SURVEY.md §5.1 — its closest artifact is
MetricLogger's iter/data timing). On TPU this is cheap and first-class:
jax.profiler trace capture around any code region, a step timer, and
model-FLOPs-utilization accounting against the chip's peak.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np

# dense peak TFLOP/s (bf16) per chip by TPU generation; used for MFU.
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0,   # v5e
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,   # trillium
    "cpu": 1.0,
}


def chip_peak_tflops() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for name, peak in PEAK_TFLOPS.items():
        if name in kind:
            return peak
    return PEAK_TFLOPS["cpu"]


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler trace capture around a region; view with tensorboard."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class _ProfileArmer:
    """On-demand device profiling: arm once, capture the next K hot regions.

    The imperative sibling of :func:`trace` for long-lived processes where
    nobody can wrap the hot loop in a ``with`` block after the fact: a serve
    worker arms via ``POST /debug/profile``, the trainer via
    ``DCR_PROFILE_AT_STEP`` — both then pass every hot region (device step /
    train step) through :meth:`capture`, which starts the jax.profiler trace
    on the first armed region, counts K regions, and stops. Unarmed,
    :meth:`capture` is two attribute reads — safe to leave permanently in
    the hot path.

    Profiler failures (an unsupported backend, a second concurrent session)
    disarm loudly into ``status()['error']`` instead of breaking the region
    they wrap: profiling must never fail the workload it measures."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._logdir: Optional[str] = None
        self._remaining = 0
        self._active = False
        self._artifact: Optional[str] = None
        self._error: Optional[str] = None

    def arm(self, logdir: str, steps: int = 1) -> dict:
        if steps < 1:
            raise ValueError(f"profile steps must be >= 1, got {steps}")
        with self._lock:
            if self._remaining or self._active:
                raise RuntimeError(
                    f"profiler already armed ({self._remaining} step(s) "
                    f"remaining into {self._logdir})")
            self._logdir = str(logdir)
            self._remaining = int(steps)
            self._artifact = None
            self._error = None
        return self.status()

    def status(self) -> dict:
        with self._lock:
            return {
                "armed": bool(self._remaining or self._active),
                "remaining": self._remaining,
                "logdir": self._logdir,
                "artifact": self._artifact,
                "error": self._error,
            }

    @contextlib.contextmanager
    def capture(self):
        """Pass one hot region through the armer. Starts the profiler trace
        when armed and not yet started; after the K-th region, stops it and
        records the artifact path."""
        if not self._remaining and not self._active:   # fast path: unarmed
            yield
            return
        start = False
        with self._lock:
            if self._remaining > 0 and not self._active:
                self._active = True
                start = True
            logdir = self._logdir
        if start:
            try:
                jax.profiler.start_trace(logdir)
            except Exception as e:      # profiler failure must not fail serving
                with self._lock:
                    self._active = False
                    self._remaining = 0
                    self._error = repr(e)
                yield
                return
        try:
            yield
        finally:
            stop = False
            with self._lock:
                if self._active and self._remaining > 0:
                    self._remaining -= 1
                    if self._remaining == 0:
                        stop = True
            if stop:
                try:
                    jax.profiler.stop_trace()
                    with self._lock:
                        self._active = False
                        self._artifact = logdir
                except Exception as e:
                    with self._lock:
                        self._active = False
                        self._error = repr(e)


_armer = _ProfileArmer()


def arm(logdir: str, steps: int = 1) -> dict:
    """Arm the process-wide profiler for the next ``steps`` captured regions
    (serve ``/debug/profile``, trainer ``DCR_PROFILE_AT_STEP``)."""
    return _armer.arm(logdir, steps)


def status() -> dict:
    return _armer.status()


def capture():
    """Context manager every profileable hot region wraps itself in; no-op
    unless :func:`arm` ran."""
    return _armer.capture()


def flops_of_jitted(jitted_fn, *args, **kwargs) -> float:
    """Per-device FLOPs of an already-jitted function from XLA's cost analysis
    (post-GSPMD-partitioning, so this is the per-chip share). 0 if
    unavailable. Extraction (list-vs-dict analysis shapes) lives in
    obs/memwatch.flops_of_compiled — the ONE implementation bench.py and the
    StepTimer MFU numbers share."""
    from dcr_tpu.obs.memwatch import flops_of_compiled

    try:
        return flops_of_compiled(jitted_fn.lower(*args, **kwargs).compile())
    except Exception:
        return 0.0


def compiled_flops(fn, *args, **kwargs) -> Optional[float]:
    """FLOPs estimate of a function from XLA's cost analysis."""
    return flops_of_jitted(jax.jit(fn), *args, **kwargs) or None


@dataclass
class StepTimer:
    """Steady-state step timing + images/sec + MFU.

    ``flops_per_step`` is the PER-DEVICE FLOP share (what
    :func:`flops_of_jitted` returns: post-GSPMD-partitioning cost analysis),
    so MFU is per-device achieved over per-device peak — dividing by
    ``device_count`` again, as an earlier revision did, under-reported MFU by
    exactly that factor. ``tflops_per_sec`` stays the per-device rate the
    flops input implies; ``tflops_per_sec_total`` scales it to the whole job.
    """

    flops_per_step: Optional[float] = None
    _t0: float = field(default_factory=time.perf_counter)
    _steps: int = 0
    _items: int = 0

    def tick(self, items: int = 0) -> None:
        self._steps += 1
        self._items += items

    def report(self, reset: bool = True) -> dict:
        dt = time.perf_counter() - self._t0
        steps = max(self._steps, 1)
        out = {
            "step_time_ms": 1e3 * dt / steps,
            "steps_per_sec": steps / dt if dt > 0 else float("inf"),
        }
        if self._items:
            out["items_per_sec"] = self._items / dt
        if self.flops_per_step:
            # per-device achieved TFLOP/s vs per-device peak: both sides of
            # the MFU ratio are per-chip, so device_count cancels
            achieved = self.flops_per_step * steps / dt / 1e12
            out["tflops_per_sec"] = achieved
            out["tflops_per_sec_total"] = achieved * jax.device_count()
            out["mfu"] = achieved / chip_peak_tflops()
        if reset:
            self._t0 = time.perf_counter()
            self._steps = self._items = 0
        return out

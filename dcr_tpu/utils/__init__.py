"""Cross-cutting utilities: profiling/MFU telemetry, git provenance."""

"""Run provenance: git state stamped into every run dir.

Equivalent of the reference's get_sha helper (utils_ret.py:420-437), wired in
rather than dead: Trainer/run_eval call :func:`stamp` so each output dir
records exactly what code produced it.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path


def _git(args: list[str], cwd: Path) -> str:
    try:
        return subprocess.run(["git", *args], cwd=cwd, capture_output=True,
                              text=True, timeout=10).stdout.strip()
    except Exception:
        return "unknown"


def describe(repo_root: Path | None = None) -> dict:
    root = repo_root or Path(__file__).resolve().parents[2]
    return {
        "sha": _git(["rev-parse", "HEAD"], root),
        "branch": _git(["rev-parse", "--abbrev-ref", "HEAD"], root),
        "dirty": bool(_git(["status", "--porcelain"], root)),
        "python": sys.version.split()[0],
        "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }


def stamp(out_dir: str | Path) -> Path:
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    path = out / "provenance.json"
    path.write_text(json.dumps(describe(), indent=2) + "\n")
    return path

"""Deterministic fault injection: the harness that proves recovery works.

A fault spec is an env/config-driven string of comma-separated entries:

    DCR_FAULTS="decode_error@step=3,ckpt_corrupt@step=200,nan_loss@step=5,sigterm@step=7"

Each entry is ``kind@key=value[&key=value...][xN]``: the fault ``kind`` fires
when a hook point reports coordinates matching EVERY ``key=value`` pair in the
entry (coordinates the entry doesn't name are ignored), at most ``N`` times
(default 1). ``@`` also separates coordinate pairs, so multi-host specs read
naturally: ``nan_loss@step=5@rank=1`` fires only on process index 1 — the
``rank`` coordinate is implicit at every hook point (filled from
``jax.process_index()``), which is how single-rank faults drive the
coordinated-recovery tests. Supported kinds and their hook points:

- ``decode_error`` — DataLoader, per sample; coords ``step``, ``slot``,
  ``index``, ``epoch``. Simulates a corrupt image: raises
  :class:`InjectedFault` through the exact code path a real decode failure
  takes (quarantine + replacement, or fail-fast when the budget is 0).
- ``ckpt_corrupt`` — CheckpointManager.save, coord ``step``: after the save
  commits, zero-fills every file in the step directory (a torn/garbage
  write), so the next restore must fall back.
- ``nan_loss`` — Trainer loop, coord ``step`` (micro-step): poisons the next
  observed loss at a log boundary, driving the rollback-or-fail-fast path.
- ``sigterm`` — Trainer loop, coord ``step``: delivers a real SIGTERM to the
  process, driving the preemption checkpoint-and-stop path.
- ``hang`` — Trainer loop, coord ``step``: wedges the host thread forever
  (a dead peer inside a collective), driving the hang-watchdog abort path
  (core/coordination.py).
- ``worker_crash`` — serve batch loop, coord ``batch`` (per-process batch
  index): SIGKILLs the serving process mid-batch — the abrupt death a fleet
  supervisor must requeue around (no drain, no flush, no exit handler).
- ``worker_hang`` — serve batch loop, coord ``batch``: wedges the worker
  thread inside the batch watchdog window, driving the exit-89 path (or the
  supervisor's dispatch-timeout kill when the watchdog is disabled).
- ``slow_step`` — serve batch loop, coord ``batch``: sleeps
  ``DCR_SLOW_STEP_S`` (default 30) seconds before the device step — a
  straggler, for latency/SLO chaos rather than death.
- ``cache_corrupt`` — warm-cache load (core/warmcache.py), coord ``load``
  (per-process load attempt index): damages the just-read entry blob in
  memory so the REAL verification path runs — quarantine rename, a
  ``warmcache/*`` fault counter, and a clean recompile. This is how CI
  proves a poisoned executable cache can never crash a boot or load a
  wrong program. ``cache_corrupt@load=0`` poisons the first load.
- ``oom`` — trainer loop (coord ``step``) and serve batch loop (coord
  ``batch``): raises a RESOURCE_EXHAUSTED-shaped :class:`InjectedOom
  <dcr_tpu.obs.memwatch.InjectedOom>` through the exact path a real XLA
  allocator failure takes — the memory-enriched flight-recorder dump
  (device stats + live-surface footprints + resident buckets) and the
  typed ``EXIT_OOM`` (85) that a fleet supervisor treats like a crash
  (journaled requests requeue, zero drops). ``oom@step=3`` kills a
  trainer after its third micro-step; ``oom@batch=0&rank=1`` kills fleet
  worker 1 on its first batch.
- ``latent_cache_corrupt`` — latent-cache shard load (data/latent_cache.py),
  coord ``load`` (per-reader shard read index): damages the just-read shard
  bytes in memory so the sha verification fails exactly like real bit rot —
  the shard is quarantine-renamed, a ``latentcache/shard_corrupt`` counter
  bumps, and its indices degrade to cache misses that the pipelined
  producer re-encodes live (``latentcache/batch_recompute``). This is how
  CI proves a damaged latent cache can never crash a run or train on wrong
  latents. ``latent_cache_corrupt@load=0`` poisons the first shard.
- ``search_dump_corrupt`` — embedding-dump load (search/embed.py), coord
  ``load`` (per-process verified-dump read index): damages the just-read
  dump bytes in memory so the sha256-sidecar verification fails exactly
  like a torn write — the load raises a typed ``EmbeddingDumpError``, a
  ``search/dump_corrupt`` counter bumps, and the calling layer (search
  folder scan, copy-risk loader) quarantines the dump. This is how CI
  proves a torn embedding dump is detected at load instead of producing a
  wrong similarity table. ``search_dump_corrupt@load=0`` poisons the first
  verified read.
- ``store_shard_corrupt`` — embedding-store shard load (search/store.py),
  coord ``load`` (per-reader shard read index): damages the just-read
  shard bytes in memory so the sha verification fails like real bit rot —
  the shard is quarantine-renamed, a ``search/store_shard_corrupt``
  counter bumps, and the store serves the surviving rows. This is how CI
  proves a damaged store can never crash a query or return scores from
  corrupt rows. ``store_shard_corrupt@load=0`` poisons the first shard.
- ``wal_torn`` — live-ingest WAL append (search/livestore.py), coord
  ``append`` (per-writer append index): writes a deliberately torn frame
  (partial payload, no commit marker) instead of the real record and
  raises without acking — exactly the bytes a crash mid-``write()``
  leaves. Recovery truncates the torn tail, bumps ``ingest/torn_total``,
  and never serves the row; the record was never acked so losing it is
  correct. ``wal_torn@append=3`` tears the fourth append.
- ``ingest_crash`` — live-ingest WAL append (search/livestore.py), coord
  ``append``: writes a partial frame then SIGKILLs the process mid-append
  — the full crash, not a simulation. The chaos e2e restarts, recovers,
  and pins the recovered store query-equal (scores AND keys) to a rebuilt
  store over the acked rows. ``ingest_crash@append=5`` kills during the
  sixth append.
- ``compact_crash`` — WAL compaction (search/livestore.py), coord ``seal``
  (per-writer compaction index): SIGKILLs after the new versioned manifest
  is written but BEFORE the atomic ``CURRENT`` flip — the worst instant.
  Recovery proves the previous snapshot still serves, the WAL replays, and
  the next compaction overwrites the orphaned manifest cleanly.
  ``compact_crash@seal=0`` kills the first compaction.
- ``ivf_list_corrupt`` — ann inverted-list load (search/ann.py), coord
  ``load`` (per-reader list read index): damages the just-read list bytes
  in memory so the sha256 verification fails like real bit rot — the list
  is quarantine-renamed, an ``ann/ivf_list_corrupt`` counter bumps, and
  the engine REBUILDS the list from the committed store (a list is a
  projection of the store, never the only copy). This is how CI proves a
  damaged ann tier degrades to a rebuild instead of crashing a query or
  silently shrinking the candidate set. ``ivf_list_corrupt@load=0``
  poisons the first list read.
- ``kmeans_nan`` — IVF training Lloyd loop (search/ann.py train_ivf),
  coord ``iter`` (per-run Lloyd iteration index): poisons the next
  centroid update with non-finite values, driving the bounded
  seed-shifted restart path — the restart is counted
  (``ann/kmeans_restart``) and a run that exhausts its restarts raises a
  typed ``AnnError`` instead of committing NaN centroids.
  ``kmeans_nan@iter=1`` poisons the second iteration.
- ``ingest_stall`` — live-ingest pump (serve/ingest.py), coord ``row``
  (rows appended so far): the appender stops acking for
  ``DCR_INGEST_STALL_S`` seconds (default 30) while the lag gauges keep
  reporting the growing backlog — rows are delayed, never dropped, so the
  drill proves the ``ingest_lag_s`` SLO objective walks ok -> breach ->
  ok with zero loss. ``ingest_stall@row=0`` stalls before the first
  append.
- ``recall_degrade`` — online recall probe (obs/recall_probe.py), coord
  ``probe`` (1-based probe index): corrupts the production shortlist THE
  PROBE JUDGES (real responses untouched), pinning that sample's recall
  to 0 — the deterministic way to drive the ``recall`` SLO objective into
  breach and back. ``recall_degrade@probe=2`` poisons the second probe;
  ``recall_degrade@rank=0x8`` poisons eight consecutive probes on fleet
  worker 0.

In a serving fleet the ``rank`` coordinate maps to the WORKER INDEX: the
supervisor exports ``DCR_WORKER_INDEX`` into each worker's environment and
that takes precedence over ``jax.process_index()`` (every fleet worker is
its own single-process jax runtime, so process_index alone would pin all
faults to 0). ``worker_crash@batch=1&rank=0`` kills fleet worker 0 during
its second batch.

The registry is process-global, parsed once from ``DCR_FAULTS`` (tests use
:func:`install`/:func:`clear`), thread-safe (loader workers fire
concurrently), and zero-cost when empty — the hot-path guard is one ``None``
check. Every fired fault emits a structured ``[fault] injected`` log line so
an injected run is distinguishable from a genuinely failing one.
"""

from __future__ import annotations

import os
import re
import threading
from dataclasses import dataclass, field
from typing import Optional

from dcr_tpu.core.resilience import log_event


class InjectedFault(RuntimeError):
    """Raised (or delivered) by an injection hook; never by production code."""


_ENTRY_RE = re.compile(r"^(?P<kind>[a-z_]+)@(?P<coords>[a-z_]+=\d+(?:[&@][a-z_]+=\d+)*)"
                       r"(?:x(?P<times>\d+))?$")


def _current_rank() -> int:
    """The implicit ``rank`` coordinate for ``@rank=`` targeting. Fleet
    worker index (DCR_WORKER_INDEX, exported by the serve supervisor) wins
    over ``jax.process_index()``: fleet workers are independent
    single-process jax runtimes, all process_index 0."""
    worker = os.environ.get("DCR_WORKER_INDEX")
    if worker:
        return int(worker)
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # jax not importable in some harness contexts
        return int(os.environ.get("PROCESS_ID", "0") or 0)


@dataclass
class FaultSpec:
    kind: str
    where: dict[str, int]
    times: int = 1
    fired: int = 0

    def matches(self, kind: str, coords: dict[str, int]) -> bool:
        if kind != self.kind or self.fired >= self.times:
            return False
        return all(k in coords and coords[k] == v for k, v in self.where.items())


def parse_faults(spec: str) -> list[FaultSpec]:
    """Parse a DCR_FAULTS string; malformed entries fail loudly (a typo'd
    injection spec silently never firing would invalidate the harness)."""
    out: list[FaultSpec] = []
    for entry in (e.strip() for e in spec.split(",") if e.strip()):
        m = _ENTRY_RE.match(entry)
        if m is None:
            raise ValueError(
                f"malformed fault entry {entry!r} "
                "(expected kind@key=value[&key=value...][xN])")
        where = {k: int(v) for k, v in
                 (pair.split("=") for pair in re.split(r"[&@]", m.group("coords")))}
        out.append(FaultSpec(kind=m.group("kind"), where=where,
                             times=int(m.group("times") or 1)))
    return out


@dataclass
class FaultRegistry:
    specs: list[FaultSpec] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def __post_init__(self) -> None:
        # resolved lazily at fire time (jax may not be initialized yet when
        # DCR_FAULTS is parsed), but only when some spec targets a rank
        self._needs_rank = any("rank" in s.where for s in self.specs)

    def __bool__(self) -> bool:
        return bool(self.specs)

    def fire(self, kind: str, **coords: int) -> bool:
        """True iff a spec matches these coordinates and still has fires left.
        Firing is atomic: concurrent hook calls can't double-spend a spec.
        Empty registry: no lock taken — hook points stay contention-free when
        injection is off (loader workers + the train thread share this)."""
        if not self.specs:
            return False
        if self._needs_rank and "rank" not in coords:
            coords["rank"] = _current_rank()
        with self._lock:
            for s in self.specs:
                if s.matches(kind, coords):
                    s.fired += 1
                    log_event("injected", kind=kind, **coords)
                    return True
        return False

    def pending(self) -> list[str]:
        """Entries that have not exhausted their fires (harness diagnostics)."""
        with self._lock:
            return [f"{s.kind}@{s.where} fired {s.fired}/{s.times}"
                    for s in self.specs if s.fired < s.times]


_registry: Optional[FaultRegistry] = None


def registry() -> FaultRegistry:
    """The process-global registry, parsed from DCR_FAULTS on first use."""
    global _registry
    if _registry is None:
        _registry = FaultRegistry(parse_faults(os.environ.get("DCR_FAULTS", "")))
    return _registry


def install(spec: str) -> FaultRegistry:
    """Replace the global registry (tests / programmatic harnesses)."""
    global _registry
    _registry = FaultRegistry(parse_faults(spec))
    return _registry


def clear() -> None:
    global _registry
    _registry = None


def fire(kind: str, **coords: int) -> bool:
    """Module-level hook point. Zero-cost when no faults are configured."""
    global _registry
    if _registry is None:
        if not os.environ.get("DCR_FAULTS"):
            return False
        registry()
    return _registry.fire(kind, **coords)

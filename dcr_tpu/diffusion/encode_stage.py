"""dcr-pipe: pipelined training — frozen-encoder producer + denoiser hot step.

The fused train step (diffusion/train.py) pays the frozen VAE encode and
(when ``train_text_encoder=False``) the frozen text encode inside the single
jitted program, every step of every run — even though the paper's experiment
matrix finetunes the *same* images under many duplication/caption/mitigation
regimes. Following DiffusionPipe (PAPERS.md: partition the frozen components
out of the hot loop of large diffusion-model training), this module splits
that program in two:

- :func:`make_encode_stage` — the **producer**: VAE-encode + frozen
  text-encode as its own ``@compile_surface`` program, run by
  :class:`EncodeProducer` on a background thread one-or-more steps ahead of
  the trainer, feeding a bounded device-side prefetch ring (the loader's
  threaded-prefetch discipline, one level up the pipeline);
- :func:`make_denoise_step` — the **consumer**: the pure denoiser+optimizer
  hot step over a :class:`HotState` (step / unet / opt / EMA — the frozen
  params never enter, so nothing frozen is donated and the producer shares
  the same frozen buffers);
- :func:`make_cache_stage` — the producer's latent-cache fast path: given
  precomputed VAE posterior moments + text embeddings
  (data/latent_cache.py), reconstruct the per-occurrence latent sample with
  the encoders never executed.

**RNG stream ownership is explicit** so the draws are unchanged between the
fused and pipelined programs: the producer owns the ``vae_sample`` stream
(keyed on the global micro-step it is encoding for), the denoiser owns
``noise`` / ``timesteps`` / ``emb_noise`` / ``mixup_beta`` / ``mixup_perm``
(keyed on ``hot.step`` exactly as the fused step keys them on
``state.step``) — the q-sample draws of step N are bit-identical either
way. The pipelined-off path does not import this module at all: the trainer
builds the original fused step body, so disabled mode is bit-identical by
construction (the fused ``train/step`` HLO digest in compile_manifest.json
does not move).

Pipelining telemetry: the producer emits ``train/data_wait`` (time blocked
on the host loader) and ``train/encode`` spans on its own thread; the
consumer emits ``train/encode_wait`` (time blocked on the ring — the
pipeline bubble) and the ``data/queue_depth`` gauge tracks ring occupancy.
``tools/trace_report.py`` renders these as the "Pipeline" section.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from dcr_tpu.core.compile_surface import compile_surface
from dcr_tpu.core.config import TrainConfig
from dcr_tpu.core.precision import policy_from_string
from dcr_tpu.core import resilience as R
from dcr_tpu.core import rng as rngmod
from dcr_tpu.core import tracing
from dcr_tpu.diffusion.train import (DiffusionModels, TrainState,
                                     make_lr_schedule, make_optimizer,
                                     resolve_scale_lr)
from dcr_tpu.models import schedulers as S
from dcr_tpu.parallel import mesh as pmesh

#: streams drawn by the producer stage; the denoiser owns the rest. One
#: list, asserted against train.py's key dict by tests, so a new stream
#: must be assigned an owner before it can ship.
PRODUCER_STREAMS = ("vae_sample",)
DENOISER_STREAMS = ("noise", "timesteps", "emb_noise", "mixup_beta",
                    "mixup_perm")


@flax.struct.dataclass
class HotState:
    """The denoiser hot step's state: everything the optimizer touches,
    nothing frozen. Donated every step; the frozen params (VAE, and the
    text encoder unless it is being trained) live OUTSIDE so the producer
    can keep encoding against the same buffers while the consumer donates."""

    step: jax.Array
    unet_params: Any
    opt_state: Any
    text_params: Optional[Any] = None   # present iff cfg.train_text_encoder
    ema_params: Optional[Any] = None


def split_state(state: TrainState, train_text_encoder: bool):
    """TrainState -> (HotState, frozen dict). Pure re-referencing: no copies,
    the views share buffers with the input state."""
    hot = HotState(
        step=state.step, unet_params=state.unet_params,
        opt_state=state.opt_state,
        text_params=state.text_params if train_text_encoder else None,
        ema_params=state.ema_params)
    frozen = {"vae": state.vae_params,
              "text": None if train_text_encoder else state.text_params}
    return hot, frozen


def merge_state(hot: HotState, frozen: dict,
                train_text_encoder: bool) -> TrainState:
    """(HotState, frozen) -> TrainState — the checkpoint/export view."""
    return TrainState(
        step=hot.step, unet_params=hot.unet_params,
        text_params=(hot.text_params if train_text_encoder
                     else frozen["text"]),
        vae_params=frozen["vae"], opt_state=hot.opt_state,
        ema_params=hot.ema_params)


def _text_ctx(models: DiffusionModels, policy, text_params, input_ids):
    out = models.text_encoder.apply(
        {"params": policy.cast_to_compute(text_params)}, input_ids)
    return out.last_hidden_state


@compile_surface("train/encode")
def make_encode_stage(cfg: TrainConfig, models: DiffusionModels, mesh, *,
                      emit: str = "latents") -> Callable:
    """Build the producer program: (frozen, batch, root_key, step) -> enc.

    ``emit="latents"`` (training) draws the per-occurrence VAE posterior
    sample with the ``vae_sample`` stream keyed on ``step`` — the identical
    key the fused step would derive at that micro-step, so the draw is
    unchanged. ``emit="moments"`` (the ``dcr-precompute-latents`` path)
    returns the posterior mean/std instead of a sample: the sample stays a
    per-occurrence train-time draw, which is what lets ONE cache serve every
    epoch and every duplication regime without freezing the latent noise.

    enc carries ``ctx`` (frozen text embedding) when the text encoder is
    frozen, or passes ``input_ids`` through when it is being trained (the
    denoiser then encodes with the live trainable params).
    """
    policy = policy_from_string(cfg.mixed_precision)
    batch_spec = pmesh.batch_sharding(mesh)

    def encode_fn(frozen: dict, batch: dict, root_key: jax.Array,
                  step: jax.Array) -> dict:
        pixels = jax.lax.with_sharding_constraint(batch["pixel_values"],
                                                  batch_spec)
        input_ids = jax.lax.with_sharding_constraint(batch["input_ids"],
                                                     batch_spec)
        vae_params_c = policy.cast_to_compute(frozen["vae"])
        dist = models.vae.apply({"params": vae_params_c},
                                policy.cast_to_compute(pixels),
                                method=models.vae.encode)
        enc: dict = {"index": batch["index"]}
        if emit == "moments":
            std = jnp.exp(0.5 * jnp.clip(dist.logvar, -30.0, 20.0))
            enc["mean"] = dist.mean.astype(jnp.float32)
            enc["std"] = std.astype(jnp.float32)
        else:
            key_vae = rngmod.step_key(
                rngmod.stream_key(root_key, "vae_sample"), step)
            latents = dist.sample(key_vae) * models.vae.config.vae_scaling_factor
            enc["latents"] = latents.astype(jnp.float32)
        if cfg.train_text_encoder:
            enc["input_ids"] = input_ids
        else:
            enc["ctx"] = _text_ctx(models, policy, frozen["text"], input_ids)
        return enc

    return jax.jit(encode_fn)


@compile_surface("train/encode_cached")
def make_cache_stage(cfg: TrainConfig, models: DiffusionModels,
                     mesh) -> Callable:
    """Build the latent-cache producer program:
    (moments, root_key, step) -> enc — the encoders never execute.

    Reconstructs the per-occurrence latent sample from cached posterior
    moments with the SAME ``vae_sample`` stream/step key the live encode
    stage would use: ``mean + std * N(key)`` in the compute dtype, scaled
    and cast exactly like ``DiagonalGaussian.sample`` — so a cache-fed run
    draws the latents a live-encode run would.
    """
    policy = policy_from_string(cfg.mixed_precision)
    batch_spec = pmesh.batch_sharding(mesh)
    if cfg.train_text_encoder:
        raise ValueError("latent-cache training requires a frozen text "
                         "encoder (validate_pipe_config enforces this)")

    def cache_fn(moments: dict, root_key: jax.Array,
                 step: jax.Array) -> dict:
        mean = jax.lax.with_sharding_constraint(moments["mean"], batch_spec)
        std = jax.lax.with_sharding_constraint(moments["std"], batch_spec)
        ctx = jax.lax.with_sharding_constraint(moments["ctx"], batch_spec)
        key_vae = rngmod.step_key(
            rngmod.stream_key(root_key, "vae_sample"), step)
        mean_c = policy.cast_to_compute(mean)
        std_c = policy.cast_to_compute(std)
        eps = jax.random.normal(key_vae, mean_c.shape, mean_c.dtype)
        latents = (mean_c + std_c * eps) * models.vae.config.vae_scaling_factor
        return {"latents": latents.astype(jnp.float32),
                "ctx": policy.cast_to_compute(ctx),
                "index": moments["index"]}

    return jax.jit(cache_fn)


@compile_surface("train/denoise")
def make_denoise_step(cfg: TrainConfig, models: DiffusionModels,
                      mesh) -> Callable:
    """Build the hot step: (hot, enc, root_key) -> (hot', metrics).

    The fused step body (diffusion/train.py) minus the frozen encoders: the
    q-sample draws (``noise``/``timesteps``) and the embedding-mitigation
    draws key on ``hot.step`` through the same streams the fused step keys
    on ``state.step``, so step N's draws are identical. Donates the hot
    state only — enc and the frozen params are never donated, which is what
    lets the producer run ahead against stable buffers.
    """
    cfg = resolve_scale_lr(cfg)
    policy = policy_from_string(cfg.mixed_precision)
    tx = make_optimizer(cfg.optim)
    lr_schedule = make_lr_schedule(cfg.optim)
    sched = models.schedule
    batch_spec = pmesh.batch_sharding(mesh)
    use_remat = cfg.remat
    accum_steps = max(1, cfg.optim.gradient_accumulation_steps)

    def hot_trainable(hot: HotState) -> dict:
        t = {"unet": hot.unet_params}
        if cfg.train_text_encoder:
            t["text_encoder"] = hot.text_params
        return t

    def step_fn(hot: HotState, enc: dict, root_key: jax.Array):
        latents = jax.lax.with_sharding_constraint(enc["latents"], batch_spec)
        bsz = latents.shape[0]
        step = hot.step

        keys = {name: rngmod.step_key(rngmod.stream_key(root_key, name), step)
                for name in DENOISER_STREAMS}

        noise = jax.random.normal(keys["noise"], latents.shape)
        timesteps = jax.random.randint(keys["timesteps"], (bsz,), 0,
                                       sched.num_train_timesteps)
        noisy_latents = S.add_noise(sched, latents, noise, timesteps)
        target = S.training_target(sched, latents, noise, timesteps)

        def loss_fn(trainable):
            if cfg.train_text_encoder:
                ids = jax.lax.with_sharding_constraint(enc["input_ids"],
                                                       batch_spec)
                ctx = _text_ctx(models, policy, trainable["text_encoder"], ids)
            else:
                ctx = jax.lax.with_sharding_constraint(enc["ctx"], batch_spec)
            if cfg.rand_noise_lam > 0:
                ctx = ctx + cfg.rand_noise_lam * jax.random.normal(
                    keys["emb_noise"], ctx.shape, ctx.dtype)
            if cfg.mixup_noise_lam > 0:
                lam = jax.random.beta(keys["mixup_beta"], cfg.mixup_noise_lam, 1.0)
                perm = jax.random.permutation(keys["mixup_perm"], bsz)
                ctx = lam * ctx + (1.0 - lam) * ctx[perm]

            unet_apply = lambda p, x, t, c: models.unet.apply({"params": p}, x, t, c)
            if use_remat:
                unet_apply = jax.checkpoint(unet_apply)
            pred = unet_apply(policy.cast_to_compute(trainable["unet"]),
                              policy.cast_to_compute(noisy_latents), timesteps,
                              policy.cast_to_compute(ctx))
            return jnp.mean((pred.astype(jnp.float32) - target) ** 2)

        trainable = hot_trainable(hot)
        loss, grads = jax.value_and_grad(loss_fn)(trainable)
        grad_norm = optax.global_norm(grads)
        updates, new_opt_state = tx.update(grads, hot.opt_state, trainable)
        new_trainable = optax.apply_updates(trainable, updates)

        new_unet = new_trainable["unet"]
        new_ema = hot.ema_params
        if hot.ema_params is not None:
            d = cfg.ema_decay
            # blend only on real optimizer updates (see train.py): under
            # MultiSteps, mini_step wraps to 0 exactly when adamw applied
            if accum_steps > 1:
                applied = new_opt_state.mini_step == 0
            else:
                applied = jnp.asarray(True)
            new_ema = jax.tree.map(
                lambda e, p: jnp.where(applied, d * e + (1.0 - d) * p, e),
                hot.ema_params, new_unet)
        new_hot = HotState(
            step=step + 1,
            unet_params=new_unet,
            opt_state=new_opt_state,
            text_params=new_trainable.get("text_encoder", hot.text_params),
            ema_params=new_ema,
        )
        metrics = {"loss": loss, "grad_norm": grad_norm,
                   "lr": lr_schedule(step // accum_steps)}
        return new_hot, metrics

    return jax.jit(step_fn, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# The producer ring
# ---------------------------------------------------------------------------

class EncodeProducer:
    """Bounded producer ring: host batches -> device encode -> the trainer.

    One background thread pulls host batches from ``source`` (a loader epoch
    iterator), runs ``encode(batch, step)`` (the live encode stage or the
    latent-cache stage — injected, so both producers share this machinery),
    and parks the encoded device batch in a ``depth``-bounded queue. The
    loader's threaded-prefetch discipline, one level up: ``safe_put``
    re-checks the stop event so teardown can never leave the producer pinned
    in ``put`` holding device buffers, and every producer-side error
    (encode failure, loader error, TooManyBadSamples) surfaces on the
    consumer's next :meth:`get`.

    Telemetry: ``train/data_wait`` + ``train/encode`` spans on the producer
    thread, the ``data/queue_depth`` gauge on every ring transition; the
    consumer-side ``train/encode_wait`` span (inside :meth:`get`) is the
    pipeline bubble trace_report's "Pipeline" section reports.
    """

    _DONE = object()

    def __init__(self, source: Iterator, encode: Callable[[Any, int], Any],
                 *, depth: int, start_step: int):
        self._source = source
        self._encode = encode
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._start_step = start_step
        self._gauge = tracing.registry().gauge("data/queue_depth")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="encode-producer")
        self._thread.start()

    def _safe_put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                self._gauge.set(float(self._q.qsize()))
                return True
            except queue.Full:
                continue
        return False

    def _run(self) -> None:
        step = self._start_step
        try:
            while not self._stop.is_set():
                # host time blocked on the data pipeline — the span the
                # fused loop emitted from the train thread moves here with
                # the wait itself
                with tracing.span("train/data_wait", step=step):
                    batch = next(self._source, None)
                if batch is None:
                    break
                with tracing.span("train/encode", step=step) as sp:
                    # dcr-hbm: hbm_peak/hbm_delta attrs on the producer's
                    # hot region (no-op where the backend has no stats)
                    from dcr_tpu.obs import memwatch

                    with memwatch.span_hbm(sp):
                        enc = self._encode(batch, step)
                if not self._safe_put((step, enc, None)):
                    return
                step += 1
        except BaseException as e:  # surface loader/encode errors to consumer
            self._safe_put((step, None, e))
            return
        self._safe_put((step, self._DONE, None))

    def get(self, step: int):
        """The encoded batch for ``step`` (producer and consumer advance in
        lockstep order), or None at end of epoch. Producer-side errors
        re-raise here, on the train thread."""
        with tracing.span("train/encode_wait", step=step):
            got_step, enc, err = self._q.get()
        self._gauge.set(float(self._q.qsize()))
        if err is not None:
            raise err
        if enc is self._DONE:
            return None
        if got_step != step:
            raise RuntimeError(
                f"encode ring out of order: got step {got_step}, "
                f"expected {step}")
        return enc

    def stop(self) -> None:
        """Tear down promptly on every exit path (preemption, NaN abort,
        epoch end): set stop, drain until the thread exits."""
        self._stop.set()
        while self._thread.is_alive():
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)


def live_encode(encode_fn: Callable, frozen: dict, mesh,
                root_key: jax.Array) -> Callable[[Any, int], Any]:
    """Producer callable running the real encoder program per batch."""
    def encode(batch, step: int):
        sharded = pmesh.shard_batch(mesh, dict(batch))
        return encode_fn(frozen, sharded, root_key, np.uint32(step))

    return encode


def cached_encode(cache_fn: Callable, reader, mesh, root_key: jax.Array,
                  fallback: Callable[[Any, int], Any]
                  ) -> Callable[[Any, int], Any]:
    """Producer callable serving latents from a verified latent cache.

    A batch whose every index is cached goes through the cache stage (the
    encoders never execute). A batch touching any missing index — a shard
    that failed verification and was quarantined, or an index the
    precompute never covered — falls back to ``fallback`` (the live encode
    stage) for the WHOLE batch and counts ``latentcache/batch_recompute``:
    the deterministic recompute path a corrupt cache degrades to.
    """
    def encode(batch, step: int):
        idx = np.asarray(batch["index"])
        rows = reader.lookup(idx)
        if rows is None:
            R.bump_counter("latentcache/batch_recompute")
            R.log_event("latent_cache_batch_recompute", step=int(step),
                        indices=[int(i) for i in idx[:8]])
            return fallback(batch, step)
        mean, std, ctx = rows
        moments = pmesh.shard_batch(
            mesh, {"mean": mean, "std": std, "ctx": ctx, "index": idx})
        return cache_fn(moments, root_key, np.uint32(step))

    return encode

"""L4a: diffusion finetuning — pjit train step, Trainer loop, mitigation hooks."""

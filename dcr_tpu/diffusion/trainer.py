"""The Trainer: wiring loop around the jitted train step.

Library-level equivalent of diff_train.py:main (328-733): builds models/data/
optimizer from a TrainConfig, runs the epoch loop with periodic sample-image
grids (reference 669-701), periodic checkpoints (709-716), metric logging
(703-705) — plus what the reference lacks: full-state resume (SURVEY.md §5.4)
and multi-host awareness (one process per host, GSPMD over the mesh).
"""

from __future__ import annotations

import logging
import time
from pathlib import Path
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dcr_tpu.core import dist
from dcr_tpu.core import resilience as R
from dcr_tpu.core.checkpoint import CheckpointManager, export_hf_layout
from dcr_tpu.core.config import TrainConfig, run_name, save_config, to_dict, validate_train_config
from dcr_tpu.core.metrics import MetricWriter
from dcr_tpu.core import rng as rngmod
from dcr_tpu.utils import faults
from dcr_tpu.data.dataset import ObjectAttributeDataset
from dcr_tpu.data.loader import DataLoader
from dcr_tpu.data.tokenizer import TokenizerBase, load_tokenizer
from dcr_tpu.diffusion import train as T
from dcr_tpu.models import schedulers as S
from dcr_tpu.models.clip_text import init_clip_text
from dcr_tpu.models.unet2d import init_unet
from dcr_tpu.models.vae import init_vae, vae_scale_factor
from dcr_tpu.parallel import mesh as pmesh

log = logging.getLogger("dcr_tpu")


@jax.jit
def _params_finite(tree) -> jax.Array:
    """True iff every floating leaf is finite (on-device reduction; used to
    reject poisoned checkpoints during NaN rollback)."""
    leaves = [jnp.all(jnp.isfinite(x)) for x in jax.tree.leaves(tree)
              if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    return jnp.all(jnp.stack(leaves)) if leaves else jnp.asarray(True)


def build_modules(cfg: TrainConfig, mesh=None) -> "T.DiffusionModels":
    """Construct the module bundle WITHOUT initializing any params.

    Module objects are static pytree-less config holders; the only arrays here
    are the (tiny) noise-schedule tables. Pairs with abstract_train_state for
    zero-memory cost-analysis lowering (bench.py FLOPs accounting)."""
    from dcr_tpu.models.clip_text import CLIPTextModel
    from dcr_tpu.models.unet2d import UNet2DCondition
    from dcr_tpu.models.vae import AutoencoderKL

    sched = S.make_schedule(
        num_train_timesteps=cfg.model.num_train_timesteps,
        beta_schedule=cfg.model.beta_schedule,
        beta_start=cfg.model.beta_start, beta_end=cfg.model.beta_end,
        prediction_type=cfg.model.prediction_type)
    return T.DiffusionModels(
        unet=UNet2DCondition(cfg.model, dtype=jnp.float32, mesh=mesh),
        vae=AutoencoderKL(cfg.model, dtype=jnp.float32),
        text_encoder=CLIPTextModel(cfg.model, dtype=jnp.float32),
        schedule=sched)


def abstract_train_state(cfg: TrainConfig, key: Optional[jax.Array] = None) -> "T.TrainState":
    """Shape-only TrainState (ShapeDtypeStruct leaves, zero device memory).

    Runs the full build_models + init_train_state pipeline under
    jax.eval_shape, so optimizer/EMA slots match the real thing exactly.
    Used to lower the train step for XLA cost analysis without allocating
    the ~GBs of SD-2.1 params."""
    def mk(k):
        models, params = build_models(cfg, k)
        return T.init_train_state(cfg, models, unet_params=params["unet"],
                                  text_params=params["text"],
                                  vae_params=params["vae"])

    return jax.eval_shape(mk, key if key is not None else jax.random.key(0))


def build_models(cfg: TrainConfig, key: jax.Array, mesh=None):
    """Initialize the module bundle + params (random init; finetuning loads a
    converted checkpoint over these via models/convert.py). Passing the mesh
    enables ring-attention sequence parallelism in the UNet when its seq axis
    is >1 (cfg.model.seq_parallel_min_seq)."""
    models = build_modules(cfg, mesh=mesh)
    ku, kv, kt = jax.random.split(key, 3)
    _, unet_params = init_unet(cfg.model, ku, model=models.unet)
    _, vae_params = init_vae(cfg.model, kv, model=models.vae)
    _, text_params = init_clip_text(cfg.model, kt, model=models.text_encoder)
    return models, {"unet": unet_params, "vae": vae_params, "text": text_params}


class Trainer:
    def __init__(self, cfg: TrainConfig, *,
                 dataset: Optional[ObjectAttributeDataset] = None,
                 tokenizer: Optional[TokenizerBase] = None,
                 sample_hook: Optional[Callable] = None,
                 pretrained_params: Optional[dict] = None):
        validate_train_config(cfg)
        dist.initialize()
        # resolve scale_lr into a private copy (the caller's config object is
        # left untouched); the serialized config.json records the effective lr
        cfg = T.resolve_scale_lr(cfg)
        self.cfg = cfg
        self.mesh = pmesh.make_mesh(cfg.mesh)
        self.out_dir = Path(cfg.output_dir)
        if dist.is_primary():
            self.out_dir.mkdir(parents=True, exist_ok=True)
            save_config(cfg, self.out_dir / "config.json")
            from dcr_tpu.utils.provenance import stamp

            stamp(self.out_dir)
        self.tokenizer = tokenizer or load_tokenizer(
            cfg.pretrained_model or None,
            vocab_size=cfg.model.text_vocab_size,
            model_max_length=cfg.model.text_max_length)
        if self.tokenizer.vocab_size > cfg.model.text_vocab_size:
            # XLA gathers clamp out-of-range ids instead of failing, so a
            # too-small embedding table would train silently wrong
            raise ValueError(
                f"tokenizer vocab ({self.tokenizer.vocab_size}) exceeds "
                f"model.text_vocab_size ({cfg.model.text_vocab_size})")
        if dist.is_primary():
            self._publish_tokenizer()
        # per-run quarantine manifest: the durable record of every recovered
        # failure (bad samples, bad checkpoints, rollbacks); one file per
        # process so loader workers on every host can record locally
        pidx = dist.process_index()
        qname = "quarantine.jsonl" if pidx == 0 else f"quarantine.p{pidx}.jsonl"
        self.quarantine = R.QuarantineManifest(self.out_dir / qname)
        self.dataset = dataset or ObjectAttributeDataset(
            cfg.data, self.tokenizer, fault=cfg.fault)
        # train_batch_size is per-device (reference semantics: per-GPU batch ×
        # num_processes, diff_train.py:556); each process loads for its local chips
        local_bs = cfg.train_batch_size * jax.local_device_count()
        self.loader = DataLoader(
            self.dataset, batch_size=local_bs,
            num_workers=cfg.data.num_workers, seed=cfg.data.seed,
            process_index=dist.process_index(), process_count=dist.process_count(),
            fault=cfg.fault, quarantine=self.quarantine)
        root = rngmod.root_key(cfg.seed)
        self.models, params = build_models(cfg, rngmod.stream_key(root, "init"),
                                           mesh=self.mesh)
        if pretrained_params:
            params.update(pretrained_params)
        self.state = T.init_train_state(
            cfg, self.models, unet_params=params["unet"],
            text_params=params["text"], vae_params=params["vae"])
        self.state = T.shard_train_state(self.state, self.mesh)
        self.step_fn = T.make_train_step(cfg, self.models, self.mesh)
        self.train_key = rngmod.stream_key(root, "train")
        # same wandb project name as the reference trainer (diff_train.py:545)
        self.writer = MetricWriter(self.out_dir / "logs", config=to_dict(cfg),
                                   use_wandb=cfg.use_wandb,
                                   wandb_project="diffrep_ft",
                                   run_name=run_name(cfg))
        self.ckpt = CheckpointManager(self.out_dir / "checkpoints",
                                      max_to_keep=cfg.checkpoints_total_limit,
                                      verify=cfg.fault.verify_checkpoints,
                                      quarantine=self.quarantine)
        self.sample_hook = sample_hook
        # recovery counters, surfaced through MetricWriter at every log
        # boundary (faults/bad_samples rides self.loader.bad_samples)
        self._rollbacks = 0
        self._ckpt_fallbacks = 0
        self._nan_pending = False

    def _publish_tokenizer(self) -> None:
        """Copy BPE vocab/merges into <output_dir>/tokenizer so every
        downstream stage (dcr-sample/mitigate on --model_path=<run>) picks up
        the SAME tokenizer automatically — the diffusers checkpoint-dir
        contract the reference relies on (diff_train.py:370-374)."""
        import shutil

        paths = (getattr(self.tokenizer, "vocab_path", None),
                 getattr(self.tokenizer, "merges_path", None))
        if all(p is not None for p in paths):
            tok_dir = self.out_dir / "tokenizer"
            tok_dir.mkdir(parents=True, exist_ok=True)
            for src, dst in zip(paths, ("vocab.json", "merges.txt")):
                src = Path(src)
                if src.resolve() == (tok_dir / dst).resolve():
                    continue
                if src.suffix == ".gz":
                    # republish decompressed — the destination name has no
                    # .gz, so a verbatim copy would be unreadable downstream
                    import gzip

                    (tok_dir / dst).write_text(
                        gzip.open(src, "rt", encoding="utf-8").read())
                else:
                    shutil.copyfile(src, tok_dir / dst)

    # -- checkpoint/resume ---------------------------------------------------

    def save(self, force: bool = False) -> None:
        self.ckpt.save(int(jax.device_get(self.state.step)), self.state, force=force)

    def maybe_resume(self) -> int:
        if self.ckpt.latest_step() is None:
            return 0
        # walk back to the newest VALID checkpoint: a torn/corrupt latest
        # step is quarantined (logged + recorded) and the previous one is
        # restored instead of crashing the resume. Raises only when EVERY
        # checkpoint is invalid — restarting from scratch silently would
        # mask the loss of the whole run.
        state, step, skipped = self.ckpt.restore_latest_valid(self.state)
        self.state = state
        self._ckpt_fallbacks += len(skipped)
        if skipped:
            log.warning("resume fell back past %d corrupt checkpoint(s): %s",
                        len(skipped), [s for s, _ in skipped])
        log.info("resumed from checkpoint step %d", step)
        return step

    def _rollback_after_nan(self, step: int, loss: float) -> bool:
        """NaN rollback-and-skip (opt-in via fault.max_rollbacks): restore the
        last good checkpoint, keep the data pointer at ``step`` so the window
        that produced the non-finite loss is fast-forwarded past, and continue.
        Returns False when rollback is disabled, exhausted, or impossible
        (no checkpoint yet) — the caller then fails fast exactly as the seed.
        """
        ft = self.cfg.fault
        if self._rollbacks >= ft.max_rollbacks:
            return False
        self.ckpt.wait()  # flush pending async writes before reading steps
        if self.ckpt.latest_step() is None:
            R.log_event("nan_rollback_impossible", at_step=step,
                        reason="no checkpoint to roll back to")
            return False
        skipped_total = 0
        while True:
            try:
                state, ckpt_step, skipped = self.ckpt.restore_latest_valid(self.state)
            except FileNotFoundError as e:
                R.log_event("nan_rollback_impossible", at_step=step, reason=repr(e))
                self._ckpt_fallbacks += skipped_total
                return False
            skipped_total += len(skipped)
            # a checkpoint written between the unchecked window's boundaries
            # can itself carry non-finite params (checksums only prove the
            # bytes round-tripped, not that they were ever sane) — rolling
            # back to it would just re-trip the guard, so quarantine it and
            # keep walking
            if _params_finite(T.trainable_of(state, self.cfg.train_text_encoder)):
                break
            self.ckpt._quarantine_step(
                ckpt_step, f"non-finite params (rollback from step {step})")
        self._ckpt_fallbacks += skipped_total
        self._rollbacks += 1
        # params/opt/EMA come from ckpt_step; the step counter is fast-
        # forwarded to the failure point so the loader (and the per-step rng
        # streams, which key off state.step) continue past the bad window
        new_step = jax.device_put(
            jnp.asarray(step, jnp.asarray(state.step).dtype), state.step.sharding)
        self.state = state.replace(step=new_step)
        self.quarantine.record(
            "nan_rollback", at_step=step, restored_step=ckpt_step, loss=loss,
            rollback=self._rollbacks, max_rollbacks=ft.max_rollbacks,
            skipped_steps=step - ckpt_step)
        return True

    def export_checkpoint(self, tag: str = "checkpoint") -> Path:
        """HF-style directory-of-subfolders export (reference save format,
        diff_train.py:709-716) for the sampler/eval stages. When EMA is enabled
        the EMA weights are what gets exported (they're the point of EMA —
        sampling uses them, matching the diffusers copy-into-unet-on-save flow)."""
        out = self.out_dir / tag
        unet_to_export = (self.state.ema_params if self.state.ema_params is not None
                          else self.state.unet_params)
        if dist.is_primary():
            export_hf_layout(
                out,
                unet=jax.device_get(unet_to_export),
                vae=jax.device_get(self.state.vae_params),
                text_encoder=jax.device_get(self.state.text_params),
                scheduler_config={
                    "num_train_timesteps": self.cfg.model.num_train_timesteps,
                    "beta_schedule": self.cfg.model.beta_schedule,
                    "beta_start": self.cfg.model.beta_start,
                    "beta_end": self.cfg.model.beta_end,
                    "prediction_type": self.cfg.model.prediction_type,
                },
                model_config=to_dict(self.cfg.model),
            )
        dist.barrier("export")
        return out

    def _step_flops(self, sharded_batch) -> float:
        """Per-device FLOPs of the compiled train step (0 if unavailable);
        feeds the MFU telemetry (SURVEY.md §5.1 — absent in the reference)."""
        from dcr_tpu.utils.profiling import flops_of_jitted

        return flops_of_jitted(self.step_fn, self.state, sharded_batch,
                               self.train_key)

    # -- preemption ----------------------------------------------------------

    def install_preemption_handler(self, signals=None) -> None:
        """SIGTERM/SIGINT → finish the current step, checkpoint, exit cleanly —
        what preemptible TPU pods need (SURVEY.md §5.3; the reference has no
        recovery story at all). Installed by the train CLI; library users
        opt in explicitly.

        The first signal sets the flag and restores the default disposition, so
        a second Ctrl-C/TERM aborts immediately (e.g. while stuck in a long
        compile before any step boundary). Handlers are uninstalled when
        train() exits. Multi-host: the flag is agreed across processes at the
        periodic sync point before anyone branches, so one host's signal can't
        desynchronize the pod's collectives."""
        import signal as _signal

        self._preempted = False
        self._preempt_signals = tuple(signals or (_signal.SIGTERM, _signal.SIGINT))

        def handler(signum, frame):
            log.warning("received signal %d: will checkpoint and stop at the "
                        "next sync point (send again to abort immediately)",
                        signum)
            self._preempted = True
            _signal.signal(signum, _signal.SIG_DFL)

        for sig in self._preempt_signals:
            _signal.signal(sig, handler)

    def _uninstall_preemption_handler(self) -> None:
        import signal as _signal

        for sig in getattr(self, "_preempt_signals", ()):
            _signal.signal(sig, _signal.SIG_DFL)
        self._preempt_signals = ()

    def _global_preempted(self) -> bool:
        """Pod-wide agreement on the preemption flag: any host signaled →
        every host stops at the same step (a tiny DCN allgather; called at
        checkpoint/log boundaries, not every step)."""
        if jax.process_count() == 1:
            return getattr(self, "_preempted", False)
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([getattr(self, "_preempted", False)]))
        return bool(np.any(flags))

    # -- the loop ------------------------------------------------------------

    def train(self) -> dict:
        cfg = self.cfg
        start_step = self.maybe_resume()
        steps_per_epoch = self.loader.steps_per_epoch()
        # All periodic cadences (log_every / save_steps / modelsavesteps /
        # max_train_steps) count SYNC steps — completed optimizer updates —
        # matching the reference's accelerate global_step semantics
        # (diff_train.py:669): with gradient_accumulation_steps=N the
        # observable cadence is every N micro-batches. Internal counting
        # (state.step, checkpoint labels, resume) stays in micro-steps so a
        # mid-accumulation preemption resumes exactly where it left off.
        accum = max(1, cfg.optim.gradient_accumulation_steps)
        # stop at whichever comes first in MICRO-batches: the requested number
        # of optimizer steps, or the end of the requested epochs (a trailing
        # partial accumulation at the epoch boundary is simply not applied —
        # accelerate's dataloader-end behavior)
        max_micro = min(cfg.max_train_steps * accum,
                        cfg.num_train_epochs * steps_per_epoch)
        max_sync = max_micro // accum
        step = start_step
        t_last, imgs_last = time.time(), 0
        last_metrics: dict = {}
        global_bs = cfg.train_batch_size * jax.device_count()
        flops_per_step: float | None = None  # filled after first compiled step
        log.info("training: %d optimizer steps (micro-batch accum %d, "
                 "%d micro/epoch), global batch %d",
                 max_sync, accum, steps_per_epoch, global_bs)
        while step < max_micro:
            epoch = step // steps_per_epoch
            for batch in self.loader.epoch(epoch, start_step=step % steps_per_epoch):
                sharded = pmesh.shard_batch(self.mesh, dict(batch))
                self.state, metrics = self.step_fn(self.state, sharded, self.train_key)
                step += 1
                imgs_last += global_bs
                # deterministic fault-injection hooks (zero-cost when
                # DCR_FAULTS is unset): nan_loss poisons the next observed
                # loss; sigterm drives the real preemption path
                if faults.fire("nan_loss", step=step):
                    self._nan_pending = True
                if faults.fire("sigterm", step=step):
                    import os
                    import signal as _signal

                    os.kill(os.getpid(), _signal.SIGTERM)
                at_sync = step % accum == 0
                sync = step // accum
                if flops_per_step is None:
                    flops_per_step = self._step_flops(sharded)
                if (at_sync and sync % cfg.log_every == 0) or step == max_micro:
                    metrics = jax.device_get(metrics)
                    if self._nan_pending:
                        metrics["loss"] = float("nan")
                        self._nan_pending = False
                    if not np.isfinite(metrics["loss"]):
                        if self._rollback_after_nan(step, float(metrics["loss"])):
                            # params restored, data pointer kept at `step` —
                            # the offending window is skipped; continue
                            t_last, imgs_last = time.time(), 0
                            continue
                        # fail fast instead of training on garbage (the
                        # reference has no such guard, SURVEY §5.2). Do NOT
                        # save: params already absorbed the non-finite update —
                        # the last periodic checkpoint is the recovery point.
                        self.ckpt.wait()  # flush pending async writes
                        raise FloatingPointError(
                            f"non-finite loss {metrics['loss']} at step {step}; "
                            f"resume from the last good checkpoint "
                            f"(step {self.ckpt.latest_step()}) under "
                            f"{self.out_dir}/checkpoints")
                    dt = time.time() - t_last
                    metrics["images_per_sec"] = imgs_last / max(dt, 1e-9)
                    if flops_per_step:
                        from dcr_tpu.utils.profiling import chip_peak_tflops

                        # flops_per_step is the per-chip share (post-partition
                        # cost analysis): per-chip achieved / per-chip peak = MFU
                        steps_done = imgs_last / global_bs
                        per_chip = flops_per_step * steps_done / max(dt, 1e-9)
                        metrics["tflops_per_sec"] = (
                            per_chip * jax.device_count() / 1e12)
                        metrics["mfu"] = per_chip / 1e12 / chip_peak_tflops()
                    # recovery counters: no retry/rollback is ever silent —
                    # each also logged a structured [fault] line when it fired
                    metrics["faults/bad_samples"] = self.loader.bad_samples
                    metrics["faults/rollbacks"] = self._rollbacks
                    metrics["faults/ckpt_fallbacks"] = self._ckpt_fallbacks
                    self.writer.scalars(sync, metrics)
                    last_metrics = {k: float(np.asarray(v)) for k, v in metrics.items()}
                    t_last, imgs_last = time.time(), 0
                if self.sample_hook and at_sync and sync % cfg.save_steps == 0:
                    self.sample_hook(self, sync)
                # preemption check BEFORE the periodic save so the same step is
                # never written twice inside the shutdown grace window.
                # Multi-host: the agreement collective must run on EVERY host or
                # none, so it happens only at the uniform log_every boundary
                # (a local flag alone must not start a collective).
                if jax.process_count() > 1:
                    check_preempt = at_sync and sync % cfg.log_every == 0
                else:
                    check_preempt = getattr(self, "_preempted", False)
                if check_preempt and self._global_preempted():
                    log.warning("preemption: checkpointing at step %d and "
                                "stopping (resume picks up here)", step)
                    self.save(force=True)
                    self.ckpt.wait()
                    self.writer.close()
                    self._uninstall_preemption_handler()
                    return last_metrics
                if at_sync and sync % cfg.modelsavesteps == 0:
                    self.save()
                if step >= max_micro:
                    break
        self.save(force=True)
        self.ckpt.wait()
        self.export_checkpoint()
        self.writer.close()
        self._uninstall_preemption_handler()
        return last_metrics

"""The Trainer: wiring loop around the jitted train step.

Library-level equivalent of diff_train.py:main (328-733): builds models/data/
optimizer from a TrainConfig, runs the epoch loop with periodic sample-image
grids (reference 669-701), periodic checkpoints (709-716), metric logging
(703-705) — plus what the reference lacks: full-state resume (SURVEY.md §5.4)
and multi-host awareness (one process per host, GSPMD over the mesh).
"""

from __future__ import annotations

import logging
import os
import time
import zlib
from pathlib import Path
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

import json as _json

from dcr_tpu.core import coordination as C
from dcr_tpu.core import dist
from dcr_tpu.core.compile_surface import compile_surface
from dcr_tpu.core import resilience as R
from dcr_tpu.core import tracing
from dcr_tpu.core.checkpoint import CheckpointManager, export_hf_layout
from dcr_tpu.core.config import TrainConfig, run_name, save_config, to_dict, validate_train_config
from dcr_tpu.core.metrics import MetricWriter
from dcr_tpu.core import rng as rngmod
from dcr_tpu.utils import faults
from dcr_tpu.utils import profiling
from dcr_tpu.data.dataset import ObjectAttributeDataset
from dcr_tpu.data.loader import DataLoader
from dcr_tpu.data.tokenizer import TokenizerBase, load_tokenizer
from dcr_tpu.diffusion import train as T
from dcr_tpu.models import schedulers as S
from dcr_tpu.models.clip_text import init_clip_text
from dcr_tpu.models.unet2d import init_unet
from dcr_tpu.models.vae import init_vae, vae_scale_factor
from dcr_tpu.obs import memwatch
from dcr_tpu.parallel import mesh as pmesh

log = logging.getLogger("dcr_tpu")


@compile_surface("train/params_finite")
@jax.jit
def _params_finite(tree) -> jax.Array:
    """True iff every floating leaf is finite (on-device reduction; used to
    reject poisoned checkpoints during NaN rollback)."""
    leaves = [jnp.all(jnp.isfinite(x)) for x in jax.tree.leaves(tree)
              if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)]
    return jnp.all(jnp.stack(leaves)) if leaves else jnp.asarray(True)


def state_fingerprint(state: "T.TrainState") -> str:
    """crc32 over this host's view of (unet params, step): a cheap cross-host
    divergence probe. Logged at end-of-run on multi-host jobs — where params
    are replicated, equal fingerprints on every rank prove the replicas
    stayed bit-identical through whatever recovery actions the run took
    (FSDP-sharded leaves hash only the local shards, so those fingerprints
    are per-host by construction). Uses the checkpoint layer's host view so
    non-addressable sharded arrays never hit a raising device_get."""
    from dcr_tpu.core.checkpoint import _host_view

    crc = 0
    for leaf in jax.tree.leaves({"unet": state.unet_params, "step": state.step}):
        arr, _, _ = _host_view(leaf)
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return f"{crc:08x}"


def build_modules(cfg: TrainConfig, mesh=None) -> "T.DiffusionModels":
    """Construct the module bundle WITHOUT initializing any params.

    Module objects are static pytree-less config holders; the only arrays here
    are the (tiny) noise-schedule tables. Pairs with abstract_train_state for
    zero-memory cost-analysis lowering (bench.py FLOPs accounting)."""
    from dcr_tpu.models.clip_text import CLIPTextModel
    from dcr_tpu.models.unet2d import UNet2DCondition
    from dcr_tpu.models.vae import AutoencoderKL

    sched = S.make_schedule(
        num_train_timesteps=cfg.model.num_train_timesteps,
        beta_schedule=cfg.model.beta_schedule,
        beta_start=cfg.model.beta_start, beta_end=cfg.model.beta_end,
        prediction_type=cfg.model.prediction_type)
    return T.DiffusionModels(
        unet=UNet2DCondition(cfg.model, dtype=jnp.float32, mesh=mesh),
        vae=AutoencoderKL(cfg.model, dtype=jnp.float32),
        text_encoder=CLIPTextModel(cfg.model, dtype=jnp.float32),
        schedule=sched)


def abstract_train_state(cfg: TrainConfig, key: Optional[jax.Array] = None) -> "T.TrainState":
    """Shape-only TrainState (ShapeDtypeStruct leaves, zero device memory).

    Runs the full build_models + init_train_state pipeline under
    jax.eval_shape, so optimizer/EMA slots match the real thing exactly.
    Used to lower the train step for XLA cost analysis without allocating
    the ~GBs of SD-2.1 params."""
    def mk(k):
        models, params = build_models(cfg, k)
        return T.init_train_state(cfg, models, unet_params=params["unet"],
                                  text_params=params["text"],
                                  vae_params=params["vae"])

    return jax.eval_shape(mk, key if key is not None else jax.random.key(0))


def build_models(cfg: TrainConfig, key: jax.Array, mesh=None):
    """Initialize the module bundle + params (random init; finetuning loads a
    converted checkpoint over these via models/convert.py). Passing the mesh
    enables ring-attention sequence parallelism in the UNet when its seq axis
    is >1 (cfg.model.seq_parallel_min_seq)."""
    models = build_modules(cfg, mesh=mesh)
    ku, kv, kt = jax.random.split(key, 3)
    _, unet_params = init_unet(cfg.model, ku, model=models.unet)
    _, vae_params = init_vae(cfg.model, kv, model=models.vae)
    _, text_params = init_clip_text(cfg.model, kt, model=models.text_encoder)
    return models, {"unet": unet_params, "vae": vae_params, "text": text_params}


class Trainer:
    def __init__(self, cfg: TrainConfig, *,
                 dataset: Optional[ObjectAttributeDataset] = None,
                 tokenizer: Optional[TokenizerBase] = None,
                 sample_hook: Optional[Callable] = None,
                 pretrained_params: Optional[dict] = None):
        validate_train_config(cfg)
        dist.initialize()
        # resolve scale_lr into a private copy (the caller's config object is
        # left untouched); the serialized config.json records the effective lr
        cfg = T.resolve_scale_lr(cfg)
        self.cfg = cfg
        # lockstep-replica mode: on backends whose compiler cannot span
        # processes (CPU PJRT — this environment's 2-process resilience
        # tests), every host computes the SAME global batch on a LOCAL mesh,
        # so replicas stay bit-identical with no cross-process XLA at all,
        # while the control plane (rendezvous, agreement, barriers,
        # checkpoint commits) runs for real over the coordination service.
        self.replica_mode = (jax.process_count() > 1
                             and not dist.xla_multiprocess_supported())
        if self.replica_mode:
            log.warning(
                "backend %r cannot compile cross-process XLA: running as "
                "lockstep replicas (local mesh per host, identical data, "
                "coordination-service control plane)", jax.default_backend())
        self.mesh = pmesh.make_mesh(
            cfg.mesh, devices=jax.local_devices() if self.replica_mode else None)
        self.out_dir = Path(cfg.output_dir)
        if dist.is_primary():
            self.out_dir.mkdir(parents=True, exist_ok=True)
            save_config(cfg, self.out_dir / "config.json")
            from dcr_tpu.utils.provenance import stamp

            stamp(self.out_dir)
        self.tokenizer = tokenizer or load_tokenizer(
            cfg.pretrained_model or None,
            vocab_size=cfg.model.text_vocab_size,
            model_max_length=cfg.model.text_max_length)
        if self.tokenizer.vocab_size > cfg.model.text_vocab_size:
            # XLA gathers clamp out-of-range ids instead of failing, so a
            # too-small embedding table would train silently wrong
            raise ValueError(
                f"tokenizer vocab ({self.tokenizer.vocab_size}) exceeds "
                f"model.text_vocab_size ({cfg.model.text_vocab_size})")
        if dist.is_primary():
            self._publish_tokenizer()
        # per-run quarantine manifest: the durable record of every recovered
        # failure (bad samples, bad checkpoints, rollbacks); one file per
        # process so loader workers on every host can record locally
        pidx = dist.process_index()
        qname = "quarantine.jsonl" if pidx == 0 else f"quarantine.p{pidx}.jsonl"
        self.quarantine = R.QuarantineManifest(self.out_dir / qname)
        # span tracing + flight recorder: per-process trace.jsonl under the
        # run dir (DCR_TRACE=0 keeps the flight-recorder ring only), and the
        # anchor for flightrec_<rank>.json on every fatal path
        tracing.configure(self.out_dir, rank=pidx)
        # dcr-hbm: periodic device.memory_stats() -> dcr_device_mem_* gauges
        # (graceful no-op on backends that report none, e.g. XLA:CPU)
        memwatch.start_sampler()
        self.dataset = dataset or ObjectAttributeDataset(
            cfg.data, self.tokenizer, fault=cfg.fault)
        # train_batch_size is per-device (reference semantics: per-GPU batch ×
        # num_processes, diff_train.py:556); each process loads for its local
        # chips. Replica mode: no slicing — every host loads the identical
        # full plan, which is what keeps the replicas bit-identical.
        local_bs = cfg.train_batch_size * jax.local_device_count()
        self.loader = DataLoader(
            self.dataset, batch_size=local_bs,
            num_workers=cfg.data.num_workers, seed=cfg.data.seed,
            process_index=0 if self.replica_mode else dist.process_index(),
            process_count=1 if self.replica_mode else dist.process_count(),
            fault=cfg.fault, quarantine=self.quarantine,
            # sliced multi-host loaders must abort via the pod agreement, not
            # a unilateral worker raise (replica mode raises symmetrically —
            # identical plans — so its local abort stays safe)
            defer_budget_abort=(dist.process_count() > 1
                                and not self.replica_mode))
        root = rngmod.root_key(cfg.seed)
        self.models, params = build_models(cfg, rngmod.stream_key(root, "init"),
                                           mesh=self.mesh)
        if pretrained_params:
            params.update(pretrained_params)
        self.state = T.init_train_state(
            cfg, self.models, unet_params=params["unet"],
            text_params=params["text"], vae_params=params["vae"])
        self.state = T.shard_train_state(self.state, self.mesh)
        # dcr-pipe: pipelined mode splits the fused step into a frozen-
        # encoder producer stage + a denoiser-only hot step
        # (diffusion/encode_stage.py). Single-host only: the producer thread
        # dispatching device programs concurrently with the consumer is a
        # collective-ordering hazard on a pod, and the fused path there is
        # already correct.
        self.pipelined = bool(cfg.pipe.enabled or cfg.pipe.latent_cache)
        if self.pipelined and jax.process_count() > 1:
            if cfg.pipe.latent_cache:
                # an explicitly configured cache must never be discarded
                # silently — the whole point of the cache contract is that
                # "slower than asked for" is an error, not a degrade
                raise ValueError(
                    "pipe.latent_cache is single-host for now (the producer "
                    "thread's device dispatch is a collective-ordering "
                    "hazard on a pod) — drop the flag on multi-host runs "
                    "or run the regime matrix on single-host workers")
            log.warning("pipelined training disabled: %d processes (the "
                        "producer thread is single-host only; training "
                        "continues on the fused step)", jax.process_count())
            R.log_event("pipelined_disabled_multihost",
                        processes=jax.process_count())
            self.pipelined = False
        if self.pipelined:
            from dcr_tpu.diffusion import encode_stage as E

            self._E = E
            self.encode_fn = E.make_encode_stage(cfg, self.models, self.mesh)
            self.denoise_fn = E.make_denoise_step(cfg, self.models, self.mesh)
            # the fused program is deliberately NOT built in pipelined mode
            # (one less resident executable); pipelined-off builds ONLY the
            # fused program, whose HLO is unchanged by this feature
            self.step_fn = None
            self._denoise_call = self.denoise_fn
            self._cache_reader = None
            self._cache_fn = None
        else:
            self.step_fn = T.make_train_step(cfg, self.models, self.mesh)
        # what the loop actually calls: the jit function by default, replaced
        # by a warm-cache AOT executable (with a one-way jit fallback) when
        # cfg.warm.dir is set (_warm_start, after restore) — so a preempted
        # pod resumes without re-paying XLA. _pf_fn mirrors this for the
        # params-finite rollback check.
        self._step_call = self.step_fn
        self._pf_fn = _params_finite
        self.train_key = rngmod.stream_key(root, "train")
        # same wandb project name as the reference trainer (diff_train.py:545)
        self.writer = MetricWriter(self.out_dir / "logs", config=to_dict(cfg),
                                   use_wandb=cfg.use_wandb,
                                   wandb_project="diffrep_ft",
                                   run_name=run_name(cfg))
        # -- distributed resilience coordinator (core/coordination.py) -------
        # every recovery decision below (NaN rollback, preemption stop,
        # bad-sample abort, fallback-restore choice) goes through a pod-wide
        # agreement so all hosts act identically at identical steps; on one
        # host the agreement degenerates to pure local logic (no collectives)
        hang_timeout = float(os.environ.get("DCR_HANG_TIMEOUT_S",
                                            cfg.fault.hang_timeout_s) or 0.0)
        coord_timeout = hang_timeout if hang_timeout > 0 else cfg.fault.barrier_timeout_s
        self.coord = C.Coordinator(timeout_s=coord_timeout,
                                   abort_on_timeout=hang_timeout > 0)
        self.coord.bad_sample_budget = (
            self.loader.epoch_bad_budget()
            if cfg.fault.max_bad_sample_frac > 0 else None)
        self.watchdog = C.HangWatchdog(hang_timeout, coordinator=self.coord)
        self.ckpt = CheckpointManager(self.out_dir / "checkpoints",
                                      max_to_keep=cfg.checkpoints_total_limit,
                                      verify=cfg.fault.verify_checkpoints,
                                      quarantine=self.quarantine,
                                      coordinator=self.coord)
        self.sample_hook = sample_hook
        # recovery counters, surfaced through MetricWriter at every log
        # boundary (faults/bad_samples rides self.loader.bad_samples)
        self._rollbacks = 0
        self._ckpt_fallbacks = 0
        self._nan_pending = False
        # set when a coordinated preemption wrote the final checkpoint; the
        # CLI turns it into coordination.EXIT_PREEMPTED for restart wrappers
        self.preempted_exit = False

    def _publish_tokenizer(self) -> None:
        """Copy BPE vocab/merges into <output_dir>/tokenizer so every
        downstream stage (dcr-sample/mitigate on --model_path=<run>) picks up
        the SAME tokenizer automatically — the diffusers checkpoint-dir
        contract the reference relies on (diff_train.py:370-374)."""
        import shutil

        paths = (getattr(self.tokenizer, "vocab_path", None),
                 getattr(self.tokenizer, "merges_path", None))
        if all(p is not None for p in paths):
            tok_dir = self.out_dir / "tokenizer"
            tok_dir.mkdir(parents=True, exist_ok=True)
            for src, dst in zip(paths, ("vocab.json", "merges.txt")):
                src = Path(src)
                if src.resolve() == (tok_dir / dst).resolve():
                    continue
                if src.suffix == ".gz":
                    # republish decompressed — the destination name has no
                    # .gz, so a verbatim copy would be unreadable downstream
                    import gzip

                    (tok_dir / dst).write_text(
                        gzip.open(src, "rt", encoding="utf-8").read())
                else:
                    shutil.copyfile(src, tok_dir / dst)

    # -- checkpoint/resume ---------------------------------------------------

    def save(self, force: bool = False) -> None:
        self.ckpt.save(int(jax.device_get(self.state.step)), self.state, force=force)

    def maybe_resume(self) -> int:
        latest = self.ckpt.latest_step()
        if jax.process_count() > 1:
            # entry into the coordinated restore must be SYMMETRIC: agree on
            # whether anyone sees a checkpoint before any host branches. A
            # host that sees none while a peer sees step N falls through into
            # the restore agreement, which fails fast on every host with the
            # per-rank proposals — instead of the two hosts deadlocking in
            # different collectives.
            views = self.coord.agree_int(-1 if latest is None else int(latest),
                                         "resume_latest")
            if max(views) < 0:
                return 0  # genuinely fresh run on every host
        elif latest is None:
            return 0
        # walk back to the newest VALID checkpoint: a torn/corrupt latest
        # step is quarantined (logged + recorded) and the previous one is
        # restored instead of crashing the resume. Raises only when EVERY
        # checkpoint is invalid — restarting from scratch silently would
        # mask the loss of the whole run.
        state, step, skipped = self.ckpt.restore_latest_valid(self.state)
        self.state = state
        self._ckpt_fallbacks += len(skipped)
        if skipped:
            log.warning("resume fell back past %d corrupt checkpoint(s): %s",
                        len(skipped), [s for s, _ in skipped])
        log.info("resumed from checkpoint step %d", step)
        return step

    def _rollback_possible(self) -> bool:
        """Cheap pre-agreement eligibility check, mirroring the guards at the
        top of :meth:`_rollback_after_nan`. Shared-filesystem checkpoints and
        a deterministic rollback counter make the answer identical on every
        host, so one NaN-seeing host can answer for the pod."""
        if self._rollbacks >= self.cfg.fault.max_rollbacks:
            return False
        self.ckpt.wait()
        return self.ckpt.latest_step() is not None

    def _rollback_after_nan(self, step: int, loss: float) -> bool:
        """NaN rollback-and-skip (opt-in via fault.max_rollbacks): restore the
        last good checkpoint, keep the data pointer at ``step`` so the window
        that produced the non-finite loss is fast-forwarded past, and continue.
        Returns False when rollback is disabled, exhausted, or impossible
        (no checkpoint yet) — the caller then fails fast exactly as the seed.
        Multi-host: callers reach here only under an agreed ROLLBACK decision,
        and the restore itself goes through the coordinated
        ``restore_latest_valid`` (all hosts restore the same step).
        """
        ft = self.cfg.fault
        if self._rollbacks >= ft.max_rollbacks:
            return False
        self.ckpt.wait()  # flush pending async writes before reading steps
        if self.ckpt.latest_step() is None:
            R.log_event("nan_rollback_impossible", at_step=step,
                        reason="no checkpoint to roll back to")
            return False
        skipped_total = 0
        while True:
            try:
                state, ckpt_step, skipped = self.ckpt.restore_latest_valid(self.state)
            except FileNotFoundError as e:
                R.log_event("nan_rollback_impossible", at_step=step, reason=repr(e))
                self._ckpt_fallbacks += skipped_total
                return False
            skipped_total += len(skipped)
            # a checkpoint written between the unchecked window's boundaries
            # can itself carry non-finite params (checksums only prove the
            # bytes round-tripped, not that they were ever sane) — rolling
            # back to it would just re-trip the guard, so quarantine it and
            # keep walking
            if self._pf_fn(T.trainable_of(state, self.cfg.train_text_encoder)):
                break
            self.ckpt._quarantine_step(
                ckpt_step, f"non-finite params (rollback from step {step})")
        self._ckpt_fallbacks += skipped_total
        self._rollbacks += 1
        # params/opt/EMA come from ckpt_step; the step counter is fast-
        # forwarded to the failure point so the loader (and the per-step rng
        # streams, which key off state.step) continue past the bad window
        new_step = jax.device_put(
            jnp.asarray(step, jnp.asarray(state.step).dtype), state.step.sharding)
        self.state = state.replace(step=new_step)
        self.quarantine.record(
            "nan_rollback", at_step=step, restored_step=ckpt_step, loss=loss,
            rollback=self._rollbacks, max_rollbacks=ft.max_rollbacks,
            skipped_steps=step - ckpt_step)
        return True

    def export_checkpoint(self, tag: str = "checkpoint") -> Path:
        """HF-style directory-of-subfolders export (reference save format,
        diff_train.py:709-716) for the sampler/eval stages. When EMA is enabled
        the EMA weights are what gets exported (they're the point of EMA —
        sampling uses them, matching the diffusers copy-into-unet-on-save flow)."""
        out = self.out_dir / tag
        unet_to_export = (self.state.ema_params if self.state.ema_params is not None
                          else self.state.unet_params)
        if dist.is_primary():
            export_hf_layout(
                out,
                unet=jax.device_get(unet_to_export),
                vae=jax.device_get(self.state.vae_params),
                text_encoder=jax.device_get(self.state.text_params),
                scheduler_config={
                    "num_train_timesteps": self.cfg.model.num_train_timesteps,
                    "beta_schedule": self.cfg.model.beta_schedule,
                    "beta_start": self.cfg.model.beta_start,
                    "beta_end": self.cfg.model.beta_end,
                    "prediction_type": self.cfg.model.prediction_type,
                },
                model_config=to_dict(self.cfg.model),
            )
        # bounded: a peer that died mid-export must become a BarrierTimeout,
        # not an eternal hang. barrier_timeout_s defaults to 0 (= wait
        # forever), so fall back to the generous allgather bound; operators
        # can still opt out globally with DCR_ALLGATHER_TIMEOUT_S=0.
        dist.barrier("export",
                     timeout_s=(self.cfg.fault.barrier_timeout_s
                                or dist.default_allgather_timeout_s()))
        return out

    def _step_flops(self, sharded_batch) -> float:
        """Per-device FLOPs of the compiled train step (0 if unavailable);
        feeds the MFU telemetry (SURVEY.md §5.1 — absent in the reference)."""
        from dcr_tpu.utils.profiling import flops_of_jitted

        return flops_of_jitted(self.step_fn, self.state, sharded_batch,
                               self.train_key)

    def _denoise_flops(self, enc) -> float:
        """Pipelined-mode MFU numerator: FLOPs of the denoiser-only hot step
        — the point of the split is exactly that this excludes the frozen
        encoders, so the reported MFU is the hot loop's."""
        from dcr_tpu.utils.profiling import flops_of_jitted

        return flops_of_jitted(self.denoise_fn, self._hot, enc,
                               self.train_key)

    # -- preemption ----------------------------------------------------------

    def install_preemption_handler(self, signals=None) -> None:
        """SIGTERM/SIGINT → finish the current step, checkpoint, exit cleanly —
        what preemptible TPU pods need (SURVEY.md §5.3; the reference has no
        recovery story at all). Installed by the train CLI; library users
        opt in explicitly.

        The first signal sets the flag and restores the default disposition, so
        a second Ctrl-C/TERM aborts immediately (e.g. while stuck in a long
        compile before any step boundary). Handlers are uninstalled when
        train() exits. Multi-host: the flag propagates through the
        fault-agreement word (core/coordination.py) at the periodic sync
        point before anyone branches, so one host's signal can't
        desynchronize the pod's collectives — the pod writes ONE synchronized
        final checkpoint and every rank exits with
        ``coordination.EXIT_PREEMPTED``."""
        import signal as _signal

        self._preempted = False
        self._preempt_signals = tuple(signals or (_signal.SIGTERM, _signal.SIGINT))

        def handler(signum, frame):
            log.warning("received signal %d: will checkpoint and stop at the "
                        "next sync point (send again to abort immediately)",
                        signum)
            self._preempted = True
            _signal.signal(signum, _signal.SIG_DFL)

        for sig in self._preempt_signals:
            _signal.signal(sig, handler)

    def _uninstall_preemption_handler(self) -> None:
        import signal as _signal

        for sig in getattr(self, "_preempt_signals", ()):
            _signal.signal(sig, _signal.SIG_DFL)
        self._preempt_signals = ()

    # -- the loop ------------------------------------------------------------

    def _global_bad_count(self) -> int:
        """This host's contribution to the pod-global bad-sample agreement.
        Replica mode: every host quarantines the IDENTICAL samples (same
        data plan), so only the primary contributes — summing all replicas
        would double-count each bad sample once per host."""
        if self.replica_mode and not dist.is_primary():
            return 0
        return self.loader.epoch_bad_count

    def _make_producer(self, epoch_iter, start_step: int):
        """dcr-pipe: the per-epoch producer — live frozen-encoder stage, or
        the latent-cache stage (with the live stage as the recompute
        fallback for quarantined/uncached indices) when a cache is loaded."""
        E = self._E
        live = E.live_encode(self.encode_fn, self._frozen, self.mesh,
                             self.train_key)
        if self._cache_reader is not None:
            encode = E.cached_encode(self._cache_fn, self._cache_reader,
                                     self.mesh, self.train_key, live)
        else:
            encode = live
        return E.EncodeProducer(epoch_iter, encode,
                                depth=self.cfg.pipe.depth,
                                start_step=start_step)

    def _warm_start(self) -> None:
        """Resolve the train step and the params-finite check through the
        persistent executable cache (core/warmcache.py): with ``warm.dir``
        set, a restarted/preempted run loads serialized executables keyed on
        avals/shardings/donation/static-config/topology instead of paying
        XLA again. Any cache problem degrades to the normal jit path —
        warm start can slow a boot down by at most one fingerprint check."""
        cfg = self.cfg
        if not cfg.warm.dir:
            return
        if jax.process_count() > 1:
            # multi-host lowering/dispatch must stay byte-identical across
            # ranks; a per-host cache hit racing a peer's compile is a skew
            # risk not worth the win here — preemption recovery on pods is
            # already coordinated at the checkpoint layer
            R.log_event("warmcache_skipped_multihost",
                        processes=jax.process_count())
            return
        from dcr_tpu.core import warmcache

        cache = warmcache.WarmCache(cfg.warm.dir)
        bs = pmesh.batch_sharding(self.mesh)
        local_bs = cfg.train_batch_size * jax.local_device_count()
        px = cfg.data.resolution
        # the EXACT pytree the loop feeds the step: the loader's Batch dict —
        # pixel_values, input_ids AND the (jit-unused but aval-relevant)
        # sample index — after pmesh.shard_batch placement
        batch_avals = {
            "pixel_values": jax.ShapeDtypeStruct(
                (local_bs, px, px, 3), jnp.float32, sharding=bs),
            "input_ids": jax.ShapeDtypeStruct(
                (local_bs, cfg.model.text_max_length), jnp.int32,
                sharding=bs),
            "index": jax.ShapeDtypeStruct(
                (local_bs,),
                # the loader stamps int64; device placement canonicalizes it
                # (int32 unless x64 is enabled) — mirror that, or the aval
                # would never match the real batch
                jax.dtypes.canonicalize_dtype(jnp.int64), sharding=bs),
        }
        static = {
            "mixed_precision": cfg.mixed_precision,
            "remat": cfg.remat,
            "train_text_encoder": cfg.train_text_encoder,
            "ema_decay": cfg.ema_decay,
            "rand_noise_lam": cfg.rand_noise_lam,
            "mixup_noise_lam": cfg.mixup_noise_lam,
            "gradient_accumulation_steps":
                cfg.optim.gradient_accumulation_steps,
            "use_8bit_adam": cfg.optim.use_8bit_adam,
            "max_grad_norm": cfg.optim.max_grad_norm,
            "train_batch_size": cfg.train_batch_size,
        }
        with R.stage("train_warm"):
            if self.pipelined:
                self._warm_start_pipelined(cache, batch_avals, static)
                res = None
            else:
                res = warmcache.aot_compile(
                    "train/step", self.step_fn,
                    (self.state, batch_avals, self.train_key),
                    static_config=static, cache=cache)
                self._step_call = warmcache.guarded(res.fn, self.step_fn,
                                                    "train/step")
            tree = T.trainable_of(self.state, cfg.train_text_encoder)
            pf = warmcache.aot_compile("train/params_finite", _params_finite,
                                       (tree,), static_config={}, cache=cache)
            self._pf_fn = warmcache.guarded(pf.fn, _params_finite,
                                            "train/params_finite")
        if res is not None:
            log.info("warm start: train/step %s in %.2fs, params_finite %s "
                     "(cache %s)", res.source, res.build_s, pf.source,
                     cfg.warm.dir)

    def _enc_avals(self, local_bs: int):
        """The encoded-batch pytree avals the denoiser hot step consumes —
        the encode stage's output contract, mirrored for AOT lowering."""
        from dcr_tpu.core.precision import policy_from_string
        from dcr_tpu.models.vae import vae_scale_factor

        cfg = self.cfg
        bs = pmesh.batch_sharding(self.mesh)
        lat = cfg.data.resolution // vae_scale_factor(cfg.model)
        policy = policy_from_string(cfg.mixed_precision)
        enc = {
            "latents": jax.ShapeDtypeStruct(
                (local_bs, lat, lat, cfg.model.vae_latent_channels),
                jnp.float32, sharding=bs),
            "index": jax.ShapeDtypeStruct(
                (local_bs,), jax.dtypes.canonicalize_dtype(jnp.int64),
                sharding=bs),
        }
        if cfg.train_text_encoder:
            enc["input_ids"] = jax.ShapeDtypeStruct(
                (local_bs, cfg.model.text_max_length), jnp.int32, sharding=bs)
        else:
            enc["ctx"] = jax.ShapeDtypeStruct(
                (local_bs, cfg.model.text_max_length,
                 cfg.model.text_hidden_size), policy.compute_dtype,
                sharding=bs)
        return enc

    def _warm_start_pipelined(self, cache, batch_avals: dict,
                              static: dict) -> None:
        """dcr-pipe warm start: pre-populate the denoiser hot step and the
        producer stage (live encode, or the latent-cache stage when a cache
        is configured) from the persistent executable cache."""
        from dcr_tpu.core import warmcache

        cfg = self.cfg
        E = self._E
        local_bs = cfg.train_batch_size * jax.local_device_count()
        enc_avals = self._enc_avals(local_bs)
        hot, frozen = E.split_state(self.state, cfg.train_text_encoder)
        # NOTE: pipe.depth is host-side ring capacity, not baked into any
        # program — keeping it out of the key means retuning the ring never
        # invalidates the warm cache (and matches surfaces.py's statics)
        step_aval = jax.ShapeDtypeStruct((), jnp.uint32)
        res = warmcache.aot_compile(
            "train/denoise", self.denoise_fn,
            (hot, enc_avals, self.train_key),
            static_config=static, cache=cache)
        self._denoise_call = warmcache.guarded(res.fn, self.denoise_fn,
                                               "train/denoise")
        if self._cache_fn is not None:
            moments = dict(self._moments_avals(local_bs),
                           index=enc_avals["index"])
            stage = warmcache.aot_compile(
                "train/encode_cached", self._cache_fn,
                (moments, self.train_key, step_aval),
                static_config=static, cache=cache)
            self._cache_fn = warmcache.guarded(stage.fn, self._cache_fn,
                                               "train/encode_cached")
        else:
            stage = warmcache.aot_compile(
                "train/encode", self.encode_fn,
                (frozen, batch_avals, self.train_key, step_aval),
                static_config=static, cache=cache)
            self.encode_fn = warmcache.guarded(stage.fn, self.encode_fn,
                                               "train/encode")
        log.info("warm start (pipelined): train/denoise %s in %.2fs, "
                 "producer stage %s in %.2fs (cache %s)", res.source,
                 res.build_s, stage.source, stage.build_s, cfg.warm.dir)

    def _moments_avals(self, local_bs: int) -> dict:
        """Latent-cache moments avals (mean/std/ctx) for AOT lowering."""
        from dcr_tpu.models.vae import vae_scale_factor

        cfg = self.cfg
        bs = pmesh.batch_sharding(self.mesh)
        lat = cfg.data.resolution // vae_scale_factor(cfg.model)
        moment = jax.ShapeDtypeStruct(
            (local_bs, lat, lat, cfg.model.vae_latent_channels), jnp.float32,
            sharding=bs)
        ctx = jax.ShapeDtypeStruct(
            (local_bs, cfg.model.text_max_length,
             cfg.model.text_hidden_size), jnp.float32, sharding=bs)
        return {"mean": moment, "std": moment, "ctx": ctx}

    def train(self) -> dict:
        try:
            return self._train_impl()
        except Exception as e:
            # dcr-hbm: XLA RESOURCE_EXHAUSTED anywhere in the loop (step,
            # encode producer, restore) becomes the typed OOM fatal path —
            # a flight-recorder dump enriched with the device-memory
            # snapshot and live-surface footprints, then exit 85, so a
            # restart wrapper can tell "shrink the batch" apart from a
            # crash. Every other exception keeps its existing semantics.
            if memwatch.is_oom_error(e):
                self.watchdog.stop()
                try:
                    at = int(jax.device_get(self.state.step))
                except Exception:  # state buffers may be donated/deleted
                    # mid-step when the allocator failed — the dump's last
                    # spans carry the step anyway
                    at = -1
                memwatch.oom_abort(f"train step {at}", e)
            raise
        finally:
            # watchdog must die with the loop on EVERY exit path: a fail-fast
            # exception (FloatingPointError, TooManyBadSamples, loader errors)
            # stops the heartbeats, and a still-armed watchdog would then
            # os._exit(EXIT_HANG) mid-unwind, masking the real failure
            self.watchdog.stop()

    def _train_impl(self) -> dict:
        cfg = self.cfg
        start_step = self.maybe_resume()
        if jax.process_count() > 1:
            # startup health check: divergent resume steps (one host restored
            # a checkpoint a peer can't see) would desynchronize every
            # collective that follows — fail fast with the per-rank values
            self.coord.assert_same("resume_step", start_step)
        # dcr-pipe: resolve the latent cache BEFORE warm start (the cache
        # stage is one of the programs to warm) and AFTER restore (the
        # fingerprint hashes the restored frozen params). A cache that
        # cannot serve this run raises LatentCacheError — training against
        # the wrong latents silently is never an option.
        if self.pipelined and cfg.pipe.latent_cache:
            from dcr_tpu.data import latent_cache as LC

            expected = LC.cache_fingerprint(
                cfg, self.dataset, self.tokenizer,
                vae_params=self.state.vae_params,
                text_params=self.state.text_params)
            with R.stage("latent_cache_load"):
                self._cache_reader = LC.LatentCacheReader(
                    cfg.pipe.latent_cache, expected)
            self._cache_fn = self._E.make_cache_stage(cfg, self.models,
                                                      self.mesh)
            cached, total = self._cache_reader.coverage()
            log.info("latent cache %s: %d/%d indices cached (misses "
                     "re-encode live)", cfg.pipe.latent_cache, cached, total)
        # dcr-warm: pre-populate the step programs from the persistent
        # executable cache AFTER restore (the state's avals/shardings are
        # final here), so a preempted pod's first step is a cache load, not
        # a recompile
        self._warm_start()
        if self.pipelined:
            self._hot, self._frozen = self._E.split_state(
                self.state, cfg.train_text_encoder)
        self.watchdog.start()
        steps_per_epoch = self.loader.steps_per_epoch()
        # All periodic cadences (log_every / save_steps / modelsavesteps /
        # max_train_steps) count SYNC steps — completed optimizer updates —
        # matching the reference's accelerate global_step semantics
        # (diff_train.py:669): with gradient_accumulation_steps=N the
        # observable cadence is every N micro-batches. Internal counting
        # (state.step, checkpoint labels, resume) stays in micro-steps so a
        # mid-accumulation preemption resumes exactly where it left off.
        accum = max(1, cfg.optim.gradient_accumulation_steps)
        # stop at whichever comes first in MICRO-batches: the requested number
        # of optimizer steps, or the end of the requested epochs (a trailing
        # partial accumulation at the epoch boundary is simply not applied —
        # accelerate's dataloader-end behavior)
        max_micro = min(cfg.max_train_steps * accum,
                        cfg.num_train_epochs * steps_per_epoch)
        max_sync = max_micro // accum
        step = start_step
        t_last, imgs_last = time.time(), 0
        last_metrics: dict = {}
        # replica mode: every host computes the same batch, so the effective
        # global batch is one replica's (counting all replicas would double-
        # count identical samples in the throughput telemetry)
        global_bs = cfg.train_batch_size * (
            jax.local_device_count() if self.replica_mode else jax.device_count())
        flops_per_step: float | None = None  # filled after first compiled step
        # on-demand device profiling (dcr-scope): DCR_PROFILE_AT_STEP=K arms
        # a jax.profiler capture around micro-steps [K, K+DCR_PROFILE_STEPS)
        # via the same utils/profiling armer serve's POST /debug/profile
        # uses; the artifact lands under <output_dir>/profile
        profile_at = int(os.environ.get("DCR_PROFILE_AT_STEP", "-1") or -1)
        profile_steps = int(os.environ.get("DCR_PROFILE_STEPS", "1") or 1)
        log.info("training: %d optimizer steps (micro-batch accum %d, "
                 "%d micro/epoch), global batch %d",
                 max_sync, accum, steps_per_epoch, global_bs)
        producer = None
        while step < max_micro:
            epoch = step // steps_per_epoch
            epoch_iter = self.loader.epoch(epoch,
                                           start_step=step % steps_per_epoch)
            # dcr-pipe: in pipelined mode the producer thread owns the
            # loader wait (train/data_wait moves to its thread) and runs the
            # frozen-encoder stage up to pipe.depth steps ahead; the train
            # thread's wait on the ring is the train/encode_wait bubble
            producer = (self._make_producer(epoch_iter, start_step=step)
                        if self.pipelined else None)
            try:
                while True:
                    if producer is None:
                        # span around the fetch: host time spent WAITING on
                        # the data pipeline (the loader's own decode work
                        # runs on its worker threads and is traced there as
                        # data/batch spans)
                        with tracing.span("train/data_wait", step=step):
                            batch = next(epoch_iter, None)
                        if batch is None:
                            break
                    else:
                        enc = producer.get(step)
                        if enc is None:
                            break
                        if flops_per_step is None:
                            # before the step: the hot state is donated by
                            # the call below, and lowering needs live avals
                            flops_per_step = self._denoise_flops(enc)
                    if step == profile_at:
                        try:
                            profiling.arm(str(self.out_dir / "profile"),
                                          profile_steps)
                            R.log_trace("profile_armed", at_step=step,
                                        steps=profile_steps)
                        except (RuntimeError, ValueError) as e:
                            R.log_event("profile_arm_failed", error=repr(e))
                    with profiling.capture():
                        # dcr-hbm: hbm_peak/hbm_delta span attrs (no-op on
                        # stats-less backends) — trace_report's Memory
                        # section aggregates resident deltas from these
                        with tracing.span("train/step", step=step) as sp, \
                                memwatch.span_hbm(sp):
                            if producer is None:
                                sharded = pmesh.shard_batch(self.mesh,
                                                            dict(batch))
                                self.state, metrics = self._step_call(
                                    self.state, sharded, self.train_key)
                            else:
                                self._hot, metrics = self._denoise_call(
                                    self._hot, enc, self.train_key)
                                # keep the checkpoint/export view current:
                                # pure re-referencing of live buffers, no
                                # copies
                                self.state = self._E.merge_state(
                                    self._hot, self._frozen,
                                    cfg.train_text_encoder)
                    step += 1
                    imgs_last += global_bs
                    self.watchdog.beat(step)
                    # deterministic fault-injection hooks (zero-cost when
                    # DCR_FAULTS is unset): nan_loss poisons the next observed
                    # loss; sigterm drives the real preemption path; hang wedges
                    # this host to drive the collective-hang watchdog; all accept
                    # an @rank= coordinate for single-host faults on a pod
                    if faults.fire("nan_loss", step=step):
                        self._nan_pending = True
                    if faults.fire("oom", step=step):
                        # deterministic RESOURCE_EXHAUSTED: propagates to
                        # train()'s OOM catch exactly like the real thing
                        # (memory-enriched flight-rec dump, exit 85)
                        raise memwatch.InjectedOom(f"train step {step}")
                    if faults.fire("sigterm", step=step):
                        import signal as _signal

                        os.kill(os.getpid(), _signal.SIGTERM)
                    if faults.fire("hang", step=step):
                        C.simulate_hang(f"injected hang at step {step}")
                    at_sync = step % accum == 0
                    sync = step // accum
                    if flops_per_step is None and producer is None:
                        flops_per_step = self._step_flops(sharded)
                    decision: Optional[C.Decision] = None
                    if (at_sync and sync % cfg.log_every == 0) or step == max_micro:
                        metrics = jax.device_get(metrics)
                        if self._nan_pending:
                            metrics["loss"] = float("nan")
                            self._nan_pending = False
                        # ONE agreement round per boundary carries the whole fault
                        # word (nan + preempt + bad samples). On a pod EVERY host
                        # exchanges here even with a locally-finite loss — a
                        # single rank's NaN must move the whole pod in lockstep,
                        # and an un-entered collective is itself a hang. One host:
                        # the exchange is pure local logic, entered only when a
                        # local flag is set.
                        nan_here = not np.isfinite(metrics["loss"])
                        if (nan_here or getattr(self, "_preempted", False)
                                or jax.process_count() > 1):
                            if nan_here:
                                self.coord.note_nan(
                                    step, rollback_ok=self._rollback_possible())
                            if getattr(self, "_preempted", False):
                                self.coord.note_preempt()
                            self.coord.note_bad_samples(self._global_bad_count())
                            decision = self.coord.exchange(step, tag="sync")
                            if decision.action is C.Action.ROLLBACK and \
                                    self._rollback_after_nan(
                                        decision.nan_step, float(metrics["loss"])):
                                # params restored, data pointer kept at the agreed
                                # step — the offending window is skipped; continue
                                if producer is not None:
                                    # re-derive the HOT view from the
                                    # restored state but KEEP the original
                                    # frozen buffers: the live producer's
                                    # closure pins them (bit-equal values —
                                    # frozen params never train), and
                                    # re-merging over them drops the
                                    # restore's duplicate frozen copy
                                    # instead of holding both in HBM until
                                    # the epoch ends
                                    self._hot, _ = self._E.split_state(
                                        self.state, cfg.train_text_encoder)
                                    self.state = self._E.merge_state(
                                        self._hot, self._frozen,
                                        cfg.train_text_encoder)
                                t_last, imgs_last = time.time(), 0
                                continue
                            if decision.action in (C.Action.ROLLBACK, C.Action.FAIL):
                                # fail fast instead of training on garbage (the
                                # reference has no such guard, SURVEY §5.2). Do NOT
                                # save: params already absorbed the non-finite
                                # update — the last periodic checkpoint is the
                                # recovery point. All hosts raise together (same
                                # decision), so no peer is left in a collective.
                                self.ckpt.wait()  # flush pending async writes
                                # fatal path: preserve the last moments (spans,
                                # fault counters) before the raise unwinds
                                tracing.dump_flight_recorder(
                                    f"nan_abort: step {decision.nan_step} loss "
                                    f"{metrics['loss']}")
                                raise FloatingPointError(
                                    f"non-finite loss {metrics['loss']} at step "
                                    f"{decision.nan_step} (ranks {list(decision.nan_ranks)}); "
                                    f"resume from the last good checkpoint "
                                    f"(step {self.ckpt.latest_step()}) under "
                                    f"{self.out_dir}/checkpoints")
                        dt = time.time() - t_last
                        metrics["images_per_sec"] = imgs_last / max(dt, 1e-9)
                        if flops_per_step:
                            from dcr_tpu.utils.profiling import chip_peak_tflops

                            # flops_per_step is the per-chip share (post-partition
                            # cost analysis): per-chip achieved / per-chip peak =
                            # MFU. One naming convention with StepTimer.report:
                            # bare tflops_per_sec is PER-DEVICE, _total is the job.
                            steps_done = imgs_last / global_bs
                            per_chip = flops_per_step * steps_done / max(dt, 1e-9)
                            metrics["tflops_per_sec"] = per_chip / 1e12
                            metrics["tflops_per_sec_total"] = (
                                per_chip * jax.device_count() / 1e12)
                            metrics["mfu"] = per_chip / 1e12 / chip_peak_tflops()
                        # recovery counters: no retry/rollback is ever silent —
                        # each also logged a structured [fault] line when it fired
                        metrics["faults/bad_samples"] = self.loader.bad_samples
                        metrics["faults/rollbacks"] = self._rollbacks
                        metrics["faults/ckpt_fallbacks"] = self._ckpt_fallbacks
                        # process-wide counters bumped below the Trainer (decode
                        # fast-path fallbacks, kv teardown/gc errors, ...)
                        for name, count in R.counters().items():
                            metrics[f"faults/{name}"] = count
                        if jax.process_count() > 1:
                            # pod-wide fault view: aggregate every host's counters
                            # over the coordination-service KV store (pure gRPC,
                            # timeout-bounded — no XLA collectives in the control
                            # plane). Symmetric: every rank reaches this boundary
                            # in lockstep, so the round can't wedge a peer.
                            rows = dist.kv_allgather(
                                _json.dumps(R.counters()), "fault_counters",
                                timeout_s=dist.default_allgather_timeout_s())
                            pod = tracing.merge_counter_rows(
                                _json.loads(r) for r in rows)
                            for name, count in pod.items():
                                metrics[f"faults_pod/{name}"] = count
                        self.writer.scalars(sync, metrics)
                        last_metrics = {k: float(np.asarray(v)) for k, v in metrics.items()}
                        t_last, imgs_last = time.time(), 0
                    if self.sample_hook and at_sync and sync % cfg.save_steps == 0:
                        self.sample_hook(self, sync)
                    # single-host preemption BETWEEN log boundaries keeps the
                    # seed's act-at-the-very-next-step behavior (pure local
                    # "exchange", no collectives). Multi-host never enters this:
                    # its agreement ran at the uniform log boundary above — a
                    # local flag alone must not start a collective.
                    if (decision is None and jax.process_count() == 1
                            and getattr(self, "_preempted", False)):
                        self.coord.note_preempt()
                        self.coord.note_bad_samples(self._global_bad_count())
                        decision = self.coord.exchange(step, tag="sync")
                    # act on the agreed decision BEFORE the periodic save so the
                    # same step is never written twice inside the shutdown window
                    if decision is not None:
                        if decision.action is C.Action.ABORT_BAD_SAMPLES:
                            from dcr_tpu.data.loader import TooManyBadSamples

                            raise TooManyBadSamples(
                                f"epoch {epoch}: {decision.bad_total} bad samples "
                                f"across {jax.process_count()} hosts exceed the "
                                f"GLOBAL quarantine budget of "
                                f"{self.coord.bad_sample_budget} "
                                f"(max_bad_sample_frac="
                                f"{cfg.fault.max_bad_sample_frac})")
                        if decision.action is C.Action.CHECKPOINT_AND_EXIT:
                            log.warning(
                                "preemption: checkpointing at step %d and "
                                "stopping (resume picks up here; signaled on "
                                "ranks %s)", step, list(decision.preempt_ranks))
                            self.save(force=True)
                            self.ckpt.wait()
                            if jax.process_count() > 1:
                                log.info("state fingerprint at step %d: %s", step,
                                         state_fingerprint(self.state))
                            self.writer.close()
                            self._uninstall_preemption_handler()
                            self.watchdog.stop()
                            self.preempted_exit = True
                            # exit-83 path: the final checkpoint is safe; record
                            # the run's last moments for the restart's operator
                            tracing.dump_flight_recorder(
                                f"preempted: checkpointed at step {step}")
                            return last_metrics
                    if at_sync and sync % cfg.modelsavesteps == 0:
                        self.save()
                    if step >= max_micro:
                        break
            finally:
                # every exit path — epoch end, preemption return, NaN abort,
                # loader error — must tear the producer down promptly so no
                # daemon thread is left dispatching device programs
                if producer is not None:
                    producer.stop()
        self.watchdog.stop()  # export/teardown below has no step heartbeat
        self.save(force=True)
        self.ckpt.wait()
        if jax.process_count() > 1:
            log.info("state fingerprint at step %d: %s", step,
                     state_fingerprint(self.state))
        self.export_checkpoint()
        self.writer.close()
        self._uninstall_preemption_handler()
        return last_metrics

"""The diffusion finetuning train step + state, GSPMD-sharded.

TPU-native re-design of the reference trainer's hot loop (diff_train.py:613-666):
one jitted function computes vae-encode → q-sample → text-encode (+ embedding
mitigations) → unet → mse(ε|v) → adamw-with-clip, with gradient sync emitted by
GSPMD over the mesh's data axes instead of DDP/NCCL (SURVEY.md §2.2). Train-time
mitigations (arXiv:2305.20086):

- ``rand_noise_lam``: Gaussian noise added to text embeddings
  (reference diff_train.py:637-638)
- ``mixup_noise_lam``: Beta(λ,1)-weighted mixup of text embeddings across the
  batch (reference diff_train.py:639-642) — here the Beta draw and permutation
  happen inside jit with explicit keys.

Unlike the reference (which saves weights only and cannot resume,
SURVEY.md §5.4), TrainState carries params + optimizer + step + EMA and is the
unit of checkpointing.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from dcr_tpu.core.compile_surface import compile_surface
from dcr_tpu.core.config import OptimConfig, TrainConfig
from dcr_tpu.core.precision import policy_from_string
from dcr_tpu.core import rng as rngmod
from dcr_tpu.models import schedulers as S
from dcr_tpu.models.clip_text import CLIPTextModel
from dcr_tpu.models.unet2d import UNet2DCondition
from dcr_tpu.models.vae import AutoencoderKL
from dcr_tpu.parallel import mesh as pmesh


class DiffusionModels(NamedTuple):
    """Static module bundle (hashable; safe to close over in jit)."""

    unet: UNet2DCondition
    vae: AutoencoderKL
    text_encoder: CLIPTextModel
    schedule: S.NoiseSchedule


@flax.struct.dataclass
class TrainState:
    step: jax.Array                       # int32 optimizer-step counter
    unet_params: Any
    text_params: Any                      # trainable iff cfg.train_text_encoder
    vae_params: Any                       # always frozen
    opt_state: Any
    ema_params: Optional[Any] = None      # EMA of unet_params when enabled


def trainable_of(state: TrainState, train_text_encoder: bool) -> dict:
    t = {"unet": state.unet_params}
    if train_text_encoder:
        t["text_encoder"] = state.text_params
    return t


def resolve_scale_lr(cfg: TrainConfig) -> TrainConfig:
    """Fold the reference's scale_lr semantics (lr × grad-accum × per-device
    batch × device count) into a NEW config with scale_lr cleared. Called by
    every optimizer-building path so direct train.py users get it too; the
    caller's config object is never mutated."""
    if not cfg.optim.scale_lr:
        return cfg
    import dataclasses

    new_optim = dataclasses.replace(
        cfg.optim, scale_lr=False,
        learning_rate=cfg.optim.learning_rate
        * cfg.optim.gradient_accumulation_steps
        * cfg.train_batch_size * jax.device_count())
    return dataclasses.replace(cfg, optim=new_optim)


def make_lr_schedule(cfg: OptimConfig) -> optax.Schedule:
    """The reference's get_scheduler surface (diff_train.py:506-511)."""
    lr = cfg.learning_rate
    warmup = cfg.lr_warmup_steps
    if cfg.lr_scheduler == "constant":
        return optax.constant_schedule(lr)
    if cfg.lr_scheduler == "constant_with_warmup":
        return optax.join_schedules(
            [optax.linear_schedule(0.0, lr, warmup), optax.constant_schedule(lr)],
            [warmup])
    if cfg.lr_scheduler == "linear":
        return optax.join_schedules(
            [optax.linear_schedule(0.0, lr, warmup),
             optax.linear_schedule(lr, 0.0, 10 ** 9)], [warmup])
    if cfg.lr_scheduler == "cosine":
        return optax.join_schedules(
            [optax.linear_schedule(0.0, lr, warmup),
             optax.cosine_decay_schedule(lr, 10 ** 6)], [warmup])
    raise ValueError(f"unknown lr_scheduler {cfg.lr_scheduler!r}")


def make_optimizer(cfg: OptimConfig) -> optax.GradientTransformation:
    """AdamW with global-norm clipping and optional scan-free grad accumulation
    (reference: AdamW diff_train.py:424-446, clip 657-663, accumulate 618;
    --use_8bit_adam -> blockwise 8-bit moment state, core/adam8bit.py)."""
    if cfg.use_8bit_adam:
        from dcr_tpu.core.adam8bit import adamw8bit as adam_factory
    else:
        adam_factory = optax.adamw
    adam = adam_factory(
        learning_rate=make_lr_schedule(cfg),
        b1=cfg.adam_beta1, b2=cfg.adam_beta2,
        eps=cfg.adam_epsilon, weight_decay=cfg.adam_weight_decay,
    )
    tx = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm), adam)
    if cfg.gradient_accumulation_steps > 1:
        tx = optax.MultiSteps(tx, cfg.gradient_accumulation_steps)
    return tx


def init_train_state(cfg: TrainConfig, models: DiffusionModels, *,
                     unet_params, text_params, vae_params) -> TrainState:
    cfg = resolve_scale_lr(cfg)
    tx = make_optimizer(cfg.optim)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        unet_params=unet_params,
        text_params=text_params,
        vae_params=vae_params,
        opt_state=tx.init(
            trainable_of(
                TrainState(jnp.zeros((), jnp.int32), unet_params, text_params,
                           vae_params, None),
                cfg.train_text_encoder)),
        ema_params=jax.tree.map(jnp.copy, unet_params) if cfg.ema_decay > 0 else None,
    )
    return state


def shard_train_state(state: TrainState, mesh) -> TrainState:
    """Place params/opt-state on the mesh: tensor-parallel rules for the UNet's
    transformer projections when the tensor axis exists, the FSDP
    largest-axis rule elsewhere, replicated otherwise; step replicated."""
    from dcr_tpu.parallel.sharding import params_sharding

    tp = mesh.shape[pmesh.TENSOR_AXIS] > 1
    param_sharding = params_sharding(
        mesh, {"unet": state.unet_params, "text": state.text_params,
               "vae": state.vae_params, "opt": state.opt_state,
               "ema": state.ema_params}, tensor_parallel=tp)
    rep = pmesh.replicated(mesh)
    return TrainState(
        step=jax.device_put(state.step, rep),
        unet_params=jax.tree.map(jax.device_put, state.unet_params,
                                 param_sharding["unet"]),
        text_params=jax.tree.map(jax.device_put, state.text_params,
                                 param_sharding["text"]),
        vae_params=jax.tree.map(jax.device_put, state.vae_params,
                                param_sharding["vae"]),
        opt_state=jax.tree.map(jax.device_put, state.opt_state,
                               param_sharding["opt"]),
        ema_params=None if state.ema_params is None else jax.tree.map(
            jax.device_put, state.ema_params, param_sharding["ema"]),
    )


@compile_surface("train/step")
def make_train_step(cfg: TrainConfig, models: DiffusionModels,
                    mesh) -> Callable:
    """Build the jitted train step: (state, batch, root_key) -> (state, metrics).

    batch: pixel_values [B,H,W,3] f32, input_ids [B,L] int32 — globally sharded
    on the mesh batch axes (use parallel.shard_batch).
    """
    cfg = resolve_scale_lr(cfg)
    policy = policy_from_string(cfg.mixed_precision)
    tx = make_optimizer(cfg.optim)
    lr_schedule = make_lr_schedule(cfg.optim)
    sched = models.schedule
    batch_spec = pmesh.batch_sharding(mesh)
    use_remat = cfg.remat
    accum_steps = max(1, cfg.optim.gradient_accumulation_steps)

    def step_fn(state: TrainState, batch: dict, root_key: jax.Array):
        pixels = jax.lax.with_sharding_constraint(batch["pixel_values"], batch_spec)
        input_ids = jax.lax.with_sharding_constraint(batch["input_ids"], batch_spec)
        bsz = pixels.shape[0]
        step = state.step

        keys = {name: rngmod.step_key(rngmod.stream_key(root_key, name), step)
                for name in ("vae_sample", "noise", "timesteps", "emb_noise",
                             "mixup_beta", "mixup_perm")}

        # frozen VAE encode (outside grad; reference relies on requires_grad_(False))
        vae_params_c = policy.cast_to_compute(state.vae_params)
        dist = models.vae.apply({"params": vae_params_c}, policy.cast_to_compute(pixels),
                                method=models.vae.encode)
        latents = dist.sample(keys["vae_sample"]) * models.vae.config.vae_scaling_factor
        latents = latents.astype(jnp.float32)

        noise = jax.random.normal(keys["noise"], latents.shape)
        timesteps = jax.random.randint(keys["timesteps"], (bsz,), 0,
                                       sched.num_train_timesteps)
        noisy_latents = S.add_noise(sched, latents, noise, timesteps)
        target = S.training_target(sched, latents, noise, timesteps)

        def text_encode(text_params):
            out = models.text_encoder.apply(
                {"params": policy.cast_to_compute(text_params)}, input_ids)
            return out.last_hidden_state

        def loss_fn(trainable):
            if cfg.train_text_encoder:
                ctx = text_encode(trainable["text_encoder"])
            else:
                ctx = jax.lax.stop_gradient(text_encode(state.text_params))
            # train-time embedding mitigations
            if cfg.rand_noise_lam > 0:
                ctx = ctx + cfg.rand_noise_lam * jax.random.normal(
                    keys["emb_noise"], ctx.shape, ctx.dtype)
            if cfg.mixup_noise_lam > 0:
                lam = jax.random.beta(keys["mixup_beta"], cfg.mixup_noise_lam, 1.0)
                perm = jax.random.permutation(keys["mixup_perm"], bsz)
                ctx = lam * ctx + (1.0 - lam) * ctx[perm]

            unet_apply = lambda p, x, t, c: models.unet.apply({"params": p}, x, t, c)
            if use_remat:
                unet_apply = jax.checkpoint(unet_apply)
            pred = unet_apply(policy.cast_to_compute(trainable["unet"]),
                              policy.cast_to_compute(noisy_latents), timesteps,
                              policy.cast_to_compute(ctx))
            return jnp.mean((pred.astype(jnp.float32) - target) ** 2)

        trainable = trainable_of(state, cfg.train_text_encoder)
        loss, grads = jax.value_and_grad(loss_fn)(trainable)
        grad_norm = optax.global_norm(grads)
        updates, new_opt_state = tx.update(grads, state.opt_state, trainable)
        new_trainable = optax.apply_updates(trainable, updates)

        new_unet = new_trainable["unet"]
        new_ema = state.ema_params
        if state.ema_params is not None:
            d = cfg.ema_decay
            # blend only on real optimizer updates: under MultiSteps accumulation,
            # mini_step wraps to 0 exactly when the inner adamw applied
            if accum_steps > 1:
                applied = new_opt_state.mini_step == 0
            else:
                applied = jnp.asarray(True)
            new_ema = jax.tree.map(
                lambda e, p: jnp.where(applied, d * e + (1.0 - d) * p, e),
                state.ema_params, new_unet)
        new_state = TrainState(
            step=step + 1,
            unet_params=new_unet,
            text_params=new_trainable.get("text_encoder", state.text_params),
            vae_params=state.vae_params,
            opt_state=new_opt_state,
            ema_params=new_ema,
        )
        # the adamw schedule inside MultiSteps advances once per accumulation
        # boundary, so report the lr actually applied
        metrics = {"loss": loss, "grad_norm": grad_norm,
                   "lr": lr_schedule(step // accum_steps)}
        return new_state, metrics

    return jax.jit(step_fn, donate_argnums=(0,))

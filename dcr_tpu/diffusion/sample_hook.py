"""Periodic in-training sample grids — the reference's visual regression check.

diff_train.py builds a DiffusionPipeline mid-training and writes an image grid
per class every save_steps (571-611 initial grid for ≤3 classes, 669-701 the
periodic regeneration, via the missing concat_h helper — SURVEY.md §2.4). Here
the hook reuses the jitted scan sampler with the live train-state params (EMA
when enabled) and writes <output_dir>/generations/step_<n>.png.
"""

from __future__ import annotations

import logging
from pathlib import Path

import numpy as np
from PIL import Image

from dcr_tpu.core import dist
from dcr_tpu.core.config import SampleConfig
from dcr_tpu.core import rng as rngmod
from dcr_tpu.eval.gallery import image_grid
from dcr_tpu.models.vae import vae_scale_factor
from dcr_tpu.parallel import mesh as pmesh
from dcr_tpu.sampling.sampler import make_sampler

log = logging.getLogger("dcr_tpu")


def make_sample_hook(*, num_inference_steps: int = 20, images_per_prompt: int = 4,
                     max_prompts: int = 3, guidance_scale: float = 7.5):
    """Returns a hook(trainer, step) for Trainer(sample_hook=...).

    Prompts per conditioning regime (reference diff_train.py:573-607):
    classlevel → first `max_prompts` classes as "An image of {cls}";
    instancelevel_* → `max_prompts` captions drawn from the training caption
    tables seeded by generation_seed (random-token captions decoded through
    the tokenizer); nolevel → the instance prompt. Grids are seeded by
    cfg.generation_seed (reference --generation_seed), independent of the
    train seed.
    """
    state = {}  # memoized jitted sampler (compile once)

    def hook(trainer, step: int) -> None:
        cfg = trainer.cfg
        if "sampler" not in state:
            px = vae_scale_factor(cfg.model) * cfg.model.sample_size
            scfg = SampleConfig(
                resolution=px, num_inference_steps=num_inference_steps,
                guidance_scale=guidance_scale, sampler="ddim",
                seed=cfg.generation_seed)
            state["sampler"] = make_sampler(scfg, trainer.models, trainer.mesh)
            style = cfg.data.class_prompt
            if style == "classlevel":
                names = trainer.dataset.classnames[:max_prompts]
                state["prompts"] = [f"An image of {c}" for c in names]
            elif style.startswith("instancelevel") and trainer.dataset.prompts:
                from dcr_tpu.sampling.prompts import sample_caption_prompts

                # active paths only: under trainsubset the grid must not be
                # conditioned on captions of images excluded from training
                # (reference truncates choicelist, diff_train.py:466-468)
                ds = trainer.dataset
                caption_lists = [ds.prompts[p]
                                 for p in (ds.paths[int(i)]
                                           for i in ds.active_indices)
                                 if p in ds.prompts]
                state["prompts"] = sample_caption_prompts(
                    caption_lists, style, max_prompts,
                    seed=cfg.generation_seed, tokenizer=trainer.tokenizer,
                    stream="train_sample_prompts")
            else:
                state["prompts"] = [cfg.data.instance_prompt]
            ids = trainer.tokenizer(state["prompts"])
            ids = np.repeat(ids, images_per_prompt, axis=0)
            # pad the batch to the mesh's data-parallel size for sharding
            dp = pmesh.data_parallel_size(trainer.mesh)
            state["real"] = len(ids)
            pad = (-len(ids)) % dp
            if pad:
                ids = np.concatenate([ids, np.repeat(ids[-1:], pad, axis=0)])
            state["ids"] = ids
            state["uncond"] = np.broadcast_to(
                trainer.tokenizer([""])[0], state["ids"].shape).copy()
        params = {
            "unet": (trainer.state.ema_params if trainer.state.ema_params
                     is not None else trainer.state.unet_params),
            "vae": trainer.state.vae_params,
            "text": trainer.state.text_params,
        }
        key = rngmod.step_key(rngmod.stream_key(
            rngmod.root_key(cfg.generation_seed), "train_samples"), step)
        images = pmesh.to_host(state["sampler"](params, state["ids"],
                                                state["uncond"], key))[: state["real"]]
        if dist.is_primary():
            grid = image_grid(list(images), cols=images_per_prompt)
            out = Path(cfg.output_dir) / "generations"
            out.mkdir(parents=True, exist_ok=True)
            grid.save(out / f"step_{step}.png")
            log.info("sample grid -> %s", out / f"step_{step}.png")
            score_sample_grid(trainer, state, step, images)

    hook.state = state             # inspectable by callers/tests
    return hook


def score_sample_grid(trainer, state: dict, step: int, images) -> None:
    """dcr-watch: score one save interval's generations against the
    configured train-embedding index (``TrainConfig.risk.index_path``) and
    emit ``risk/*`` gauges through MetricWriter — the papers'
    duplication→copying effect appears LIVE on the loss-curve timeline
    instead of in a post-hoc eval job.

    Called on the PRIMARY only (the index scores on a local 1-device mesh,
    so there is no collective to diverge on); the index is memoized in the
    hook's ``state``; every failure — bad dump, scoring error — degrades to
    unscored grids with a ``copy_risk/*`` counter, never a failed step.
    ``trainer`` only needs ``.cfg`` and ``.writer`` (stub-testable).
    """
    cfg = trainer.cfg
    rcfg = getattr(cfg, "risk", None)
    if rcfg is None or not rcfg.index_path:
        return
    from dcr_tpu.core import resilience as R
    from dcr_tpu.core import tracing

    if "risk_index" not in state:
        from dcr_tpu.obs.copyrisk import CopyRiskIndex

        try:
            state["risk_index"] = CopyRiskIndex.load(
                rcfg, batch=len(images), warm_dir=cfg.warm.dir)
        except Exception as e:
            R.log_event("risk_index_load_failed", path=rcfg.index_path,
                        error=repr(e))
            R.bump_counter("copy_risk/index_load_failed")
            state["risk_index"] = None
    index = state["risk_index"]
    if index is None:
        return
    from dcr_tpu.obs import copyrisk

    try:
        with tracing.span("risk/score", step=step, batch=len(images)) as sp:
            scores = index.score_batch(images)
            agg = copyrisk.observe_scores(scores, rcfg.threshold)
            sp.attrs.update(sims=[round(s.max_sim, 6) for s in scores],
                            flagged=agg["flagged"])
    except Exception as e:
        R.log_event("risk_score_failed", step=step, error=repr(e))
        R.bump_counter("copy_risk/score_failed")
        return
    trainer.writer.scalars(step, {
        "risk/max_sim": agg["max_sim"],
        "risk/mean_sim": agg["mean_sim"],
        "risk/flagged": agg["flagged"],
        "risk/scored": agg["scored"],
    })
    log.info("risk: step %d — max_sim %.4f, %d/%d over threshold %.3f",
             step, agg["max_sim"], agg["flagged"], agg["scored"],
             rcfg.threshold)

"""L4b: bulk sampling — jit-compiled scan samplers, CFG, prompt pipelines."""

"""Training-free fast sampling: score reuse + step skipping inside the scan.

PFDiff (arXiv:2408.08822) observes that a diffusion ODE solver's score
evaluations change slowly along the trajectory, so past scores can stand in
for the current one — and that the first-order error of doing so largely
cancels inside higher-order solver updates. Just-in-Time (arXiv:2603.10744)
makes the same bet spatially: slowly-changing activations are cached across
steps instead of recomputed. This module is the temporal form for this
repo's ``lax.scan`` samplers: a host-computed per-step **plan** of
``full | reuse`` entries (like :func:`~dcr_tpu.sampling.sampler.
sampler_grid`, pure static config) where

- a **full** step runs the 2B-row CFG UNet call exactly as today and banks
  the guided prediction + its timestep in the scan carry;
- a **reuse** step skips the UNet entirely (``lax.cond`` — XLA executes one
  branch, so the FLOPs are really saved) and substitutes the banked score:
  first-order reuse when one score is banked, second-order past-difference
  extrapolation ``ε̂(t) = ε_last + (ε_last − ε_prev)·(t − t_last)/(t_last −
  t_prev)`` once two are.

The solver update (:func:`~dcr_tpu.sampling.sampler.scheduler_step`) runs
on EVERY step with whichever prediction it got, so dpm++'s second-order
multistep state advances through skipped steps exactly as through full
ones. The plan is batch-uniform static config — part of the serve
:class:`~dcr_tpu.serve.queue.GenBucket` and the bulk ``SampleConfig`` — so
each (bucket, fast-plan) is a distinct compiled program that flows through
the compile manifest, the warm cache, and the recompile budget like every
other surface, and the serve purity contract (alone-vs-mixed-batch
bit-identity) is untouched: every row of a batch follows the same plan,
and the reuse math is elementwise over the batch.

With the plan all-``full`` (fast disabled, or ``reuse_ratio=0``) the
samplers build their ORIGINAL scan body — not a degenerate fast body — so
the disabled path is bit-identical to the pre-fast sampler by
construction (tested in tests/test_fastsample.py).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

#: Hard cap on the reuse fraction a plan will accept: beyond this the bank
#: goes stale enough that even second-order extrapolation drifts visibly,
#: and the mandatory full steps (head + final) can no longer be honored at
#: small step counts.
MAX_REUSE_RATIO = 0.75

#: Leading steps that always run full: step 0 has nothing banked, step 1
#: banks the second score so second-order extrapolation is live from the
#: first possible reuse step.
_FULL_HEAD = 2


def fast_plan(num_steps: int, reuse_ratio: float) -> tuple[bool, ...]:
    """Per-step plan, ``True`` = full UNet call, ``False`` = score reuse.

    Host-computed static config (the moral twin of ``sampler_grid``):
    deterministic in (num_steps, reuse_ratio). Invariants:

    - the first two steps and the final step are always full (nothing is
      banked at step 0; a full final step pins the trajectory endpoint the
      same way diffusers' ``lower_order_final`` does);
    - ``round(reuse_ratio * num_steps)`` reuse steps, capped by the
      eligible interior, spread evenly so the bank never goes stale in one
      long run of skips;
    - ``reuse_ratio <= 0`` or a trajectory too short to skip anything
      (fewer than 4 steps) degrades to all-full — never an error.
    """
    if not 0.0 <= reuse_ratio <= MAX_REUSE_RATIO:
        raise ValueError(
            f"reuse_ratio must be in [0, {MAX_REUSE_RATIO}], got {reuse_ratio}")
    plan = [True] * num_steps
    eligible = list(range(_FULL_HEAD, num_steps - 1))
    n_reuse = min(int(round(reuse_ratio * num_steps)), len(eligible))
    if reuse_ratio <= 0.0 or n_reuse <= 0:
        return tuple(plan)
    m = len(eligible)
    # floor((i + 0.5) * m / n) is strictly increasing for n <= m: evenly
    # spread, no duplicates, deterministic
    for i in range(n_reuse):
        plan[eligible[int((i + 0.5) * m // n_reuse)]] = False
    return tuple(plan)


def unet_calls(plan: tuple[bool, ...]) -> int:
    """Full (UNet-calling) steps in a plan."""
    return sum(1 for full in plan if full)


def is_dense(plan: tuple[bool, ...]) -> bool:
    """True when the plan skips nothing — the samplers then build their
    original scan body, keeping the disabled path bit-identical."""
    return all(plan)


def canonical_plan_params(steps: int, fast_ratio: float,
                          fast_order: int) -> tuple[float, int]:
    """Canonical ``(fast_ratio, fast_order)`` for a bucket/program identity.

    Every parameterization whose PLAN is dense — ratio 0, a ratio that
    rounds to zero skips, or a trajectory too short to skip (< 4 steps) —
    builds the byte-identical original scan body, and ``fast_order`` only
    enters the program on reuse steps. Mapping them all onto ``(0.0, 2)``
    keeps one bucket identity / admission slot / compiled program /
    executable-cache key per distinct program. Invalid values pass through
    unchanged so validation still rejects them loudly."""
    if (fast_order in (1, 2) and 0.0 <= fast_ratio <= MAX_REUSE_RATIO
            and is_dense(fast_plan(steps, fast_ratio))):
        return 0.0, 2
    return fast_ratio, fast_order


class ScoreBank(NamedTuple):
    """Scan-carried past scores: the last two banked guided predictions and
    their (float) timesteps. A NamedTuple of arrays — a pytree, so it rides
    the ``lax.scan`` carry next to the latent and the dpm++ state."""

    pred: jax.Array       # last banked prediction (post-CFG), x-shaped
    prev_pred: jax.Array  # the one before it
    t: jax.Array          # float32 scalar: timestep of ``pred``
    prev_t: jax.Array     # float32 scalar: timestep of ``prev_pred``
    count: jax.Array      # int32 scalar: how many scores were ever banked


def bank_init(shape: tuple[int, ...], dtype=jnp.float32) -> ScoreBank:
    return ScoreBank(pred=jnp.zeros(shape, dtype),
                     prev_pred=jnp.zeros(shape, dtype),
                     t=jnp.zeros((), jnp.float32),
                     prev_t=jnp.zeros((), jnp.float32),
                     count=jnp.zeros((), jnp.int32))


def bank_update(bank: ScoreBank, pred: jax.Array, t: jax.Array) -> ScoreBank:
    """Push a freshly computed prediction (a full step just ran)."""
    return ScoreBank(pred=pred, prev_pred=bank.pred,
                     t=jnp.asarray(t, jnp.float32), prev_t=bank.t,
                     count=bank.count + 1)


def reuse_score(bank: ScoreBank, t: jax.Array, order: int) -> jax.Array:
    """The substitute prediction for a reuse step at timestep ``t``.

    ``order`` is static config: 1 = plain reuse of the last banked score
    (PFDiff's zeroth/first-order past reuse); 2 = past-difference linear
    extrapolation once two scores are banked (runtime-gated on
    ``bank.count`` — the first reuse step after a single full step still
    gets plain reuse). The plan guarantees at least one full step ran
    before any reuse step, so the bank is never empty here.
    """
    if order < 2:
        return bank.pred
    dt = bank.t - bank.prev_t
    slope = (bank.pred - bank.prev_pred) / jnp.where(dt == 0.0, 1.0, dt)
    extrap = bank.pred + slope * (jnp.asarray(t, jnp.float32) - bank.t)
    return jnp.where(bank.count >= 2, extrap, bank.pred)


def predict_or_reuse(plan: tuple[bool, ...], step_idx: jax.Array,
                     t: jax.Array, bank: ScoreBank, order: int,
                     full_fn) -> tuple[jax.Array, ScoreBank]:
    """One plan dispatch inside the scan body.

    ``full_fn() -> pred`` runs the real (UNet + CFG) prediction; it is
    traced into the ``lax.cond`` full branch, so on a reuse step XLA
    executes only the (cheap, elementwise) reuse branch — the denoiser
    FLOPs are genuinely skipped at runtime, while the whole trajectory
    stays one compiled scan. The plan tuple is baked in as a program
    constant: a different plan is a different program.
    """
    flags = jnp.asarray(np.asarray(plan, dtype=bool))

    def full(ops):
        bank = ops
        pred = full_fn()
        return pred, bank_update(bank, pred, t)

    def reuse(ops):
        bank = ops
        return reuse_score(bank, t, order), bank

    return jax.lax.cond(flags[step_idx], full, reuse, bank)

"""Prompt-list construction per conditioning style + inference-time augmentations.

Behavioral port of diff_inference.py:121-176 and the shared prompt_augmentation
helper (diff_inference.py:14-30 == sd_mitigation.py:14-30 — deduplicated here):

- nolevel: the constant prompt, repeated
- classlevel: seeded choice over the Imagenette class templates
- instancelevel_blip / instancelevel_ogcap: seeded choice over first captions
  from the caption json
- instancelevel_random: same, then token-id literal decoded via the tokenizer
- augmentations (mitigations): rand_numb_add / rand_word_add / rand_word_repeat,
  each inserting `repeat_num` tokens at random positions
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

from dcr_tpu.core.rng import host_python_rng
from dcr_tpu.data.captions import IMAGENETTE_CLASSES, insert_rand_word
from dcr_tpu.data.tokenizer import TokenizerBase


def prompt_augmentation(prompt: str, aug_style: str, *, tokenizer: TokenizerBase,
                        rng: np.random.Generator, repeat_num: int = 2,
                        rand_token_high: int = 49400) -> str:
    if aug_style == "rand_numb_add":
        for _ in range(repeat_num):
            prompt = insert_rand_word(prompt, str(int(rng.integers(0, 100000))), rng)
    elif aug_style == "rand_word_add":
        for _ in range(repeat_num):
            word = tokenizer.decode([int(rng.integers(0, rand_token_high))])
            prompt = insert_rand_word(prompt, word, rng)
    elif aug_style == "rand_word_repeat":
        words = prompt.split(" ")
        for _ in range(repeat_num):
            word = str(words[int(rng.integers(0, len(words)))])
            prompt = insert_rand_word(prompt, word, rng)
    else:
        raise ValueError(f"unknown prompt augmentation {aug_style!r}")
    return prompt


def sample_caption_prompts(caption_lists: Sequence[Sequence[str]], style: str,
                           count: int, *, seed: int,
                           tokenizer: TokenizerBase,
                           stream: str = "prompt_list") -> list[str]:
    """`count` seeded draws over the FIRST caption of each image's caption
    list (reference semantics: choicelist = [x[0] for x in prompts.values()],
    diff_train.py:462-463); instancelevel_random entries are token-id
    literals decoded through the tokenizer. Shared by the inference prompt
    builder and the in-training sample-grid hook."""
    choicelist = [str(caps[0]) for caps in caption_lists if caps]
    if not choicelist:
        raise ValueError("no captions to sample prompts from")
    rng = host_python_rng(seed, stream)
    # draws are WITH replacement (reference np.random.choice), so count may
    # exceed the table size
    picks = [choicelist[int(i)]
             for i in rng.integers(0, len(choicelist), size=count)]
    if style == "instancelevel_random":
        picks = [tokenizer.decode([int(t) for t in ast.literal_eval(p)])
                 for p in picks]
    return picks


def build_prompt_list(style: str, count: int, *, seed: int,
                      tokenizer: TokenizerBase,
                      instance_prompt: str = "An image",
                      classnames: Sequence[str] = IMAGENETTE_CLASSES,
                      caption_json: Optional[str | Path] = None,
                      rand_augs: Optional[str] = None,
                      rand_aug_repeats: int = 2) -> list[str]:
    rng = host_python_rng(seed, "prompt_list")
    if style == "nolevel":
        prompts = [instance_prompt] * count
    elif style == "classlevel":
        prompts = [f"An image of {classnames[i]}"
                   for i in rng.integers(0, len(classnames), size=count)]
    elif style in ("instancelevel_blip", "instancelevel_random", "instancelevel_ogcap"):
        if caption_json is None:
            raise ValueError(f"{style} needs a caption_json")
        table = json.loads(Path(caption_json).read_text())
        # fresh "prompt_list" stream == the draw sequence this branch always
        # used (rng above is untouched before this point)
        prompts = sample_caption_prompts(list(table.values()), style, count,
                                         seed=seed, tokenizer=tokenizer)
    else:
        raise ValueError(f"unknown conditioning style {style!r}")

    if rand_augs and rand_augs != "none":
        if style != "instancelevel_blip":
            # reference invariant (diff_inference.py:241-242)
            raise ValueError("prompt augmentations require instancelevel_blip prompts")
        aug_rng = host_python_rng(seed, "prompt_augs")
        prompts = [prompt_augmentation(p, rand_augs, tokenizer=tokenizer,
                                       rng=aug_rng, repeat_num=rand_aug_repeats)
                   for p in prompts]
    return prompts


def save_prompts(prompts: Sequence[str], savepath: str | Path) -> Path:
    """prompts.txt next to generations/ (reference diff_inference.py:179-181);
    eval's SynthDataset reads it back."""
    path = Path(savepath) / "prompts.txt"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(f"{p}\n" for p in prompts))
    return path

"""Bulk generation pipeline: checkpoint -> prompts -> sharded sampling -> PNGs.

Library equivalent of diff_inference.py:main (43-201) and sd_mitigation.py:main
(43-113): loads an HF-layout checkpoint dir (as written by Trainer.export_checkpoint,
matching the reference's save format), builds the prompt list for the model's
conditioning style, runs the jitted scan sampler over prompt batches, and writes
<savepath>/generations/{count}.png + prompts.txt — the exact directory contract
the eval stage consumes (diff_retrieval.py:125-126).

Instead of parsing config back out of path substrings (the reference's
filesystem-as-config pattern, diff_inference.py:44-81), the model's own
config.json is read from the checkpoint dir.
"""

from __future__ import annotations

import contextlib
import json
import logging
from pathlib import Path
from typing import Any, NamedTuple, Optional, Sequence

import jax
import numpy as np
from PIL import Image

from dcr_tpu.core import dist
from dcr_tpu.core import tracing
from dcr_tpu.core.checkpoint import import_hf_layout
from dcr_tpu.core.config import ModelConfig, SampleConfig, from_dict
from dcr_tpu.core import rng as rngmod
from dcr_tpu.data.tokenizer import TokenizerBase, load_tokenizer
from dcr_tpu.diffusion.train import DiffusionModels
from dcr_tpu.models import schedulers as S
from dcr_tpu.models.clip_text import CLIPTextModel
from dcr_tpu.models.unet2d import UNet2DCondition
from dcr_tpu.models.vae import AutoencoderKL
from dcr_tpu.parallel import mesh as pmesh
from dcr_tpu.parallel.sharding import params_sharding
from dcr_tpu.sampling import fastsample
from dcr_tpu.sampling.prompts import build_prompt_list, save_prompts
from dcr_tpu.sampling.sampler import make_sampler

log = logging.getLogger("dcr_tpu")


def load_checkpoint_models(ckpt_dir: str | Path, mesh=None):
    """(models, params) from an HF-layout dir written by Trainer.export_checkpoint.
    Model shapes come from model_index.json (our serialized ModelConfig).

    Passing a mesh with a seq axis >1 enables ring/Ulysses sequence-parallel
    attention inside the sampler's UNet (same mechanism as training) — the
    long-context inference path for 512px+ latents."""
    ckpt_dir = Path(ckpt_dir)
    index = json.loads((ckpt_dir / "model_index.json").read_text())
    if "model_config" in index:
        # round-2+ export: our native ModelConfig nested under "model_config"
        cfg_dict = index["model_config"]
    elif "block_out_channels" in index:
        # round-1 legacy flat dict, whose CLIPTextModel hardcoded quick_gelu —
        # preserve those numerics when the key predates the text_act field
        cfg_dict = {**index, "text_act": index.get("text_act", "quick_gelu")}
    else:
        # a GENUINE diffusers checkpoint directory (e.g. downloaded SD-2.1):
        # infer dims from the per-subfolder config.json files
        from dcr_tpu.core.checkpoint import model_config_from_diffusers

        cfg_dict = model_config_from_diffusers(ckpt_dir)
    model_cfg = from_dict(ModelConfig, cfg_dict)
    params = {
        "unet": import_hf_layout(ckpt_dir, "unet"),
        "vae": import_hf_layout(ckpt_dir, "vae"),
        "text": import_hf_layout(ckpt_dir, "text_encoder"),
    }
    models = DiffusionModels(
        unet=UNet2DCondition(model_cfg, mesh=mesh),
        vae=AutoencoderKL(model_cfg),
        text_encoder=CLIPTextModel(model_cfg),
        # model_cfg carries the schedule fields for every checkpoint flavor:
        # native exports round-trip them; the genuine-diffusers path fills
        # them from scheduler_config.json (model_config_from_diffusers)
        schedule=S.make_schedule(
            num_train_timesteps=model_cfg.num_train_timesteps,
            beta_schedule=model_cfg.beta_schedule,
            beta_start=model_cfg.beta_start, beta_end=model_cfg.beta_end,
            prediction_type=model_cfg.prediction_type),
    )
    _validate_loaded(models, model_cfg, params, ckpt_dir)
    return models, params, model_cfg


def _validate_loaded(models: "DiffusionModels", model_cfg: ModelConfig,
                     params: dict, ckpt_dir: Path) -> None:
    """Strict structural check of loaded trees against the architectures the
    config describes (shapes from jax.eval_shape — trace-only, no compute).
    Catches unsupported checkpoints (wrong dims, SDXL-family leftovers)
    loudly instead of sampling garbage from a partially-consumed state dict."""
    import jax.numpy as jnp

    from dcr_tpu.models.convert import check_converted

    key = jax.random.key(0)
    px = 2 ** (len(model_cfg.vae_block_out_channels) - 1) * model_cfg.sample_size
    expected = {
        "unet": jax.eval_shape(
            models.unet.init, key,
            jax.ShapeDtypeStruct((1, model_cfg.sample_size,
                                  model_cfg.sample_size,
                                  model_cfg.in_channels), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1, model_cfg.text_max_length,
                                  model_cfg.cross_attention_dim), jnp.float32),
        )["params"],
        "vae": jax.eval_shape(
            models.vae.init, key,
            jax.ShapeDtypeStruct((1, px, px, 3), jnp.float32), key)["params"],
        "text": jax.eval_shape(
            models.text_encoder.init, key,
            jax.ShapeDtypeStruct((1, model_cfg.text_max_length), jnp.int32),
        )["params"],
    }
    problems = [f"{comp}{p}" for comp in expected
                for p in check_converted(expected[comp], params[comp])]
    if problems:
        head = "; ".join(problems[:8])
        raise ValueError(
            f"checkpoint {ckpt_dir} does not match the architecture its "
            f"configs describe ({len(problems)} mismatches): {head}")


def resolve_checkpoint(cfg: SampleConfig) -> Path:
    """checkpoint_<iternum>/ or checkpoint/ under the run dir
    (reference convention, diff_inference.py:85-88)."""
    root = Path(cfg.model_path)
    if (root / "unet").exists():  # already a checkpoint dir
        return root
    if cfg.iternum and cfg.iternum > 0:
        cand = root / f"checkpoint_{cfg.iternum}"
        if not cand.exists():
            raise FileNotFoundError(f"no checkpoint_{cfg.iternum} under {root}")
        return cand
    cand = root / "checkpoint"
    if not cand.exists():
        raise FileNotFoundError(f"no exported checkpoint/ under {root} "
                                "(run Trainer.export_checkpoint or pass iternum)")
    return cand


class GenerationStack(NamedTuple):
    """Everything a generation path needs, loaded once: static modules, mesh-placed
    params, the model config, the tokenizer the checkpoint shipped with, and the
    device mesh. Shared by the bulk pipeline (:func:`generate`) and the online
    serving worker (dcr_tpu/serve/worker.py) so the two load paths cannot drift."""

    models: DiffusionModels
    params: dict
    model_cfg: ModelConfig
    tokenizer: TokenizerBase
    mesh: Any


def load_generation_stack(cfg: SampleConfig, *,
                          mesh=None,
                          tokenizer: Optional[TokenizerBase] = None,
                          models=None, params=None) -> GenerationStack:
    """checkpoint dir -> :class:`GenerationStack`, params placed on the mesh.

    ``models``/``params`` may be passed pre-built (tests, in-process benches);
    then only tokenizer resolution and mesh placement happen here. Placement
    rules match training: tensor-axis meshes shard the big matmul weights
    Megatron-style, fsdp axes shard by largest-divisible-dim, anything else
    replicates — so a model too big for one chip's HBM still loads without
    code changes.
    """
    mesh = mesh if mesh is not None else pmesh.make_mesh(cfg.mesh)
    if models is None:
        ckpt = resolve_checkpoint(cfg)
        models, params, model_cfg = load_checkpoint_models(ckpt, mesh=mesh)
    else:
        model_cfg = models.unet.config
    tokenizer = tokenizer or load_tokenizer(
        cfg.model_path or None,
        vocab_size=models.text_encoder.config.text_vocab_size,
        model_max_length=models.text_encoder.config.text_max_length)
    tensor_parallel = mesh.shape.get(pmesh.TENSOR_AXIS, 1) > 1
    params = jax.device_put(
        params, params_sharding(mesh, params, tensor_parallel=tensor_parallel))
    return GenerationStack(models=models, params=params, model_cfg=model_cfg,
                           tokenizer=tokenizer, mesh=mesh)


def generate(cfg: SampleConfig, *, modelstyle: str,
             tokenizer: Optional[TokenizerBase] = None,
             caption_json: Optional[str] = None,
             prompts: Optional[Sequence[str]] = None,
             models=None, params=None) -> Path:
    """Run bulk generation; returns the savepath containing generations/."""
    dist.initialize()
    stack = load_generation_stack(cfg, tokenizer=tokenizer,
                                  models=models, params=params)
    models, params = stack.models, stack.params
    tokenizer, mesh = stack.tokenizer, stack.mesh

    if prompts is None:
        prompts = build_prompt_list(
            modelstyle, cfg.num_batches, seed=cfg.seed, tokenizer=tokenizer,
            caption_json=caption_json,
            rand_augs=cfg.rand_augs if cfg.rand_augs != "none" else None,
            rand_aug_repeats=cfg.rand_aug_repeats)
    savepath = Path(cfg.savepath or "inferences/run")
    gen_dir = savepath / "generations"
    if dist.is_primary():
        gen_dir.mkdir(parents=True, exist_ok=True)
        save_prompts(prompts, savepath)

    sampler = make_sampler(cfg, models, mesh)
    uncond_ids = tokenizer([""])[0]
    key = rngmod.root_key(cfg.seed)
    # fast-sampling accounting (dcr-fast): static per config, so the
    # denoiser-call reduction is known without touching the device. The
    # canonical params fold every dense-degraded parameterization onto the
    # true dense identity (one executable-cache key per distinct program).
    fast_ratio, fast_order = fastsample.canonical_plan_params(
        cfg.num_inference_steps,
        cfg.fast.reuse_ratio if cfg.fast.enabled else 0.0, cfg.fast.order)
    plan = fastsample.fast_plan(cfg.num_inference_steps, fast_ratio)
    unet_calls = fastsample.unet_calls(plan)

    count = 0
    # fixed device batch (prompts_per_batch × im_batch, padded up to a multiple
    # of the data-parallel size) so every chunk hits the same compiled program
    dp = pmesh.data_parallel_size(mesh)
    prompts_per_batch = max(1, len(jax.devices()) // max(1, cfg.im_batch))
    device_batch = -(-prompts_per_batch * cfg.im_batch // dp) * dp
    if cfg.warm.dir and jax.process_count() == 1:
        # dcr-warm: the fixed-shape bulk sampler resolves through the
        # persistent executable cache — a re-run of the same (config,
        # topology) starts generating without an XLA compile. Any cache
        # problem degrades to the jit path (guarded).
        from dcr_tpu.core import warmcache

        ids_aval = jax.ShapeDtypeStruct(
            (device_batch, len(uncond_ids)), np.asarray(uncond_ids).dtype)
        res = warmcache.aot_compile(
            "sample/sampler", sampler,
            (params, ids_aval, ids_aval,
             rngmod.step_key(rngmod.stream_key(key, "sample"), 0)),
            static_config={
                "resolution": cfg.resolution,
                "num_inference_steps": cfg.num_inference_steps,
                "guidance_scale": cfg.guidance_scale,
                "sampler": cfg.sampler,
                "rand_noise_lam": cfg.rand_noise_lam,
                "im_batch": cfg.im_batch,
                "device_batch": device_batch,
                # the fast plan is baked into the program: a different plan
                # must be a different executable-cache key — and the
                # CANONICAL params above key every dense-degraded
                # parameterization the same as the true dense run (no
                # spurious warm-cache miss from an irrelevant knob)
                "fast_ratio": fast_ratio,
                "fast_order": fast_order,
            },
            cache=warmcache.WarmCache(cfg.warm.dir))
        log.info("bulk sampler %s via warm cache (%s) in %.2fs",
                 res.source, cfg.warm.dir, res.build_s)
        sampler = warmcache.guarded(res.fn, sampler, "sample/sampler")
    for start in range(0, len(prompts), prompts_per_batch):
        chunk = list(prompts[start:start + prompts_per_batch])
        ids = tokenizer(chunk)                              # [P, L]
        ids = np.repeat(ids, cfg.im_batch, axis=0)          # [P*im_batch, L]
        real = len(ids)
        if real < device_batch:                             # pad to fixed batch
            ids = np.concatenate(
                [ids, np.repeat(ids[-1:], device_batch - real, axis=0)])
        unc = np.broadcast_to(uncond_ids, ids.shape).copy()
        batch_key = rngmod.step_key(rngmod.stream_key(key, "sample"), start)
        # one sample/fast span per accelerated batch execution (args.batch
        # = trajectories in it) feeds trace_report's "Fast sampling"
        # section; dense runs keep their pre-fast trace shape
        fast_span = (tracing.span("sample/fast",
                                  steps=cfg.num_inference_steps,
                                  unet_calls=unet_calls, batch=real,
                                  fast_ratio=fast_ratio,
                                  fast_order=fast_order,
                                  sampler=cfg.sampler)
                     if unet_calls < cfg.num_inference_steps
                     else contextlib.nullcontext())
        with fast_span:
            images = pmesh.to_host(sampler(params, ids, unc, batch_key))[:real]
        if dist.is_primary():
            for img in images:
                arr = (img * 255).round().astype(np.uint8)
                im = Image.fromarray(arr)
                if im.size[0] > cfg.resolution:  # reference resize-down (195-198)
                    im = im.resize((cfg.resolution, cfg.resolution), Image.LANCZOS)
                im.save(gen_dir / f"{count}.png")
                count += 1
        else:
            count += len(images)
    log.info("wrote %d generations to %s", count, gen_dir)
    return savepath

"""Jitted text-to-image sampler: one lax.scan over denoising steps with CFG.

TPU re-design of the reference's per-prompt diffusers pipeline loop
(diff_inference.py:183-193: python loop over 50 scheduler steps per batch).
Here the whole trajectory is a single compiled scan — no host↔device chatter —
and the prompt batch is sharded over the mesh's data axes, so bulk generation
(BASELINE config 3: 10k samples) is one jit running across chips.

Inference-time mitigation ``rand_noise_lam`` reproduces the reference's Newpipe
(diff_inference.py:3-6): Gaussian noise scaled by λ added to the prompt
embeddings (both the conditional and unconditional halves, matching diffusers'
_encode_prompt which returns the concatenated pair).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from dcr_tpu.core.compile_surface import compile_surface
from dcr_tpu.core.config import SampleConfig, validate_fast_config
from dcr_tpu.core import rng as rngmod
from dcr_tpu.diffusion.train import DiffusionModels
from dcr_tpu.models import schedulers as S
from dcr_tpu.models.vae import vae_scale_factor
from dcr_tpu.parallel import mesh as pmesh
from dcr_tpu.sampling import fastsample


def encode_prompts(models: DiffusionModels, text_params, input_ids: jax.Array,
                   uncond_ids: jax.Array, *, rand_noise_lam: float = 0.0,
                   key: Optional[jax.Array] = None) -> tuple[jax.Array, jax.Array]:
    """(cond, uncond) embeddings [B, L, D]; optional Newpipe-style noise."""
    cond = models.text_encoder.apply({"params": text_params}, input_ids).last_hidden_state
    uncond = models.text_encoder.apply({"params": text_params}, uncond_ids).last_hidden_state
    if rand_noise_lam > 0.0:
        assert key is not None
        k1, k2 = jax.random.split(key)
        cond = cond + rand_noise_lam * jax.random.normal(k1, cond.shape, cond.dtype)
        uncond = uncond + rand_noise_lam * jax.random.normal(k2, uncond.shape, uncond.dtype)
    return cond, uncond


def sampler_grid(sampler: str, sched, num_inference_steps: int):
    """(ts, prev_ts, lower_order_final) for a sampler name — the single source
    of the per-sampler diffusers-parity wiring, tested directly against the
    reference fixture in tests/test_scheduler_parity.py.

    - spacing follows the diffusers scheduler each sampler maps to: linspace
      for DPMSolverMultistep, leading for DDIM/DDPM;
    - steps_offset=1 is the SD scheduler-config value (DDIM/PNDM family);
      diffusers' DDPMScheduler uses no offset;
    - final-step target: DPMSolverMultistep steps to t=0, and SD's DDIM config
      has set_alpha_to_one=False (final acp = alphas_cumprod[0]) — both are our
      prev_t=0. DDPM's terminal variance uses acp=1 (prev_t=-1);
    - lower_order_final mirrors diffusers: first-order final step when <15 steps.
    """
    spacing = "linspace" if sampler == "dpm++" else "leading"
    offset = 0 if sampler == "ddpm" else 1
    ts = S.inference_timesteps(sched, num_inference_steps, spacing=spacing,
                               steps_offset=offset)
    final_prev = -1 if sampler == "ddpm" else 0
    prev_ts = jnp.concatenate([ts[1:], jnp.array([final_prev], ts.dtype)])
    return ts, prev_ts, num_inference_steps < 15


def fast_plan_grid(sampler: str, sched, num_inference_steps: int,
                   reuse_ratio: float = 0.0):
    """:func:`sampler_grid` plus the fast-sampling step plan: ``(ts,
    prev_ts, lower_order_final, plan)`` where ``plan[i]`` is True for a
    full (UNet-calling) step and False for a score-reuse step
    (:mod:`dcr_tpu.sampling.fastsample`). The timestep grids are EXACTLY
    ``sampler_grid``'s — fast sampling skips score evaluations, never
    solver steps' positions — so ``reuse_ratio=0`` returns the identical
    grid with an all-full plan (tested)."""
    ts, prev_ts, lower_order_final = sampler_grid(sampler, sched,
                                                  num_inference_steps)
    plan = fastsample.fast_plan(num_inference_steps, reuse_ratio)
    return ts, prev_ts, lower_order_final, plan


def scheduler_step(sampler: str, sched, pred: jax.Array, x: jax.Array,
                   t, prev_t, dpm_state, *, force_first_order=False,
                   noise_key: Optional[jax.Array] = None):
    """One denoising update ``x_t -> x_{prev_t}`` for a sampler name —
    the single dispatch both the bulk pipeline (:func:`make_sampler`) and the
    serving worker (dcr_tpu/serve/worker.py) call, so a scheduler-parity fix
    lands in every generation path at once. Returns ``(x_new, dpm_state)``;
    ``noise_key`` is required only for the ancestral ``ddpm`` sampler."""
    if sampler == "ddim":
        return S.ddim_step(sched, pred, x, t, prev_t), dpm_state
    if sampler == "dpm++":
        return S.dpmpp_2m_step(sched, pred, x, t, prev_t, dpm_state,
                               force_first_order=force_first_order)
    if sampler == "ddpm":
        assert noise_key is not None, "ddpm needs a per-step noise key"
        return S.ddpm_step(sched, pred, x, t, prev_t, noise_key), dpm_state
    raise ValueError(f"unknown sampler {sampler!r}")


@compile_surface("sample/sampler")
def make_sampler(cfg: SampleConfig, models: DiffusionModels, mesh):
    """Build the jitted sampler: (params, input_ids, uncond_ids, key) -> images.

    images: [B, H, W, 3] float32 in [0, 1]. params = {"unet", "vae", "text"}.

    The UNet's module mesh is reconciled with the sampling mesh here, for
    every caller: ring/Ulysses sequence-parallel attention gates on
    ``module.mesh``, so an absent one would silently sample dense under a
    seq-axis mesh, and a stale one (e.g. a training mesh captured at
    build_models time) would shard_map over the wrong device set. Modules
    are static config — rebuilding is free.
    """
    wants_seq = mesh.shape.get(pmesh.SEQ_AXIS, 1) > 1
    target_mesh = mesh if wants_seq else None
    if models.unet.mesh is not target_mesh:
        from dcr_tpu.models.unet2d import UNet2DCondition

        models = models._replace(
            unet=UNet2DCondition(models.unet.config, dtype=models.unet.dtype,
                                 mesh=target_mesh))
    sched = models.schedule
    latent_size = cfg.resolution // vae_scale_factor(models.vae.config)
    latent_ch = models.vae.config.vae_latent_channels
    scaling = models.vae.config.vae_scaling_factor
    guidance = cfg.guidance_scale
    batch_spec = pmesh.batch_sharding(mesh)

    # bad fast knobs fail HERE, loudly and typed — the serve path gets this
    # from validate_bucket, and an invalid order must never silently run as
    # a different order (reuse_score treats order<2 as plain reuse)
    validate_fast_config(cfg.fast)
    # host-precomputed timestep grid [T] + fast step plan (see fast_plan_grid;
    # all-full unless cfg.fast enables score reuse)
    reuse_ratio = cfg.fast.reuse_ratio if cfg.fast.enabled else 0.0
    ts, prev_ts, lower_order_final, plan = fast_plan_grid(
        cfg.sampler, sched, cfg.num_inference_steps, reuse_ratio)
    # dense plan => build the ORIGINAL scan body (no cond, no score bank in
    # the carry): the fast-disabled program is bit-identical by construction
    use_fast = not fastsample.is_dense(plan)

    def sample_fn(params, input_ids, uncond_ids, key):
        input_ids = jax.lax.with_sharding_constraint(input_ids, batch_spec)
        bsz = input_ids.shape[0]
        kp, kn, ks = (rngmod.stream_key(key, n) for n in ("emb_noise", "init", "steps"))
        cond, uncond = encode_prompts(models, params["text"], input_ids, uncond_ids,
                                      rand_noise_lam=cfg.rand_noise_lam, key=kp)
        ctx = jnp.concatenate([uncond, cond], axis=0)  # [2B, L, D]

        x = jax.random.normal(kn, (bsz, latent_size, latent_size, latent_ch))
        # (diffusers scales initial noise by init_noise_sigma = 1 for DDPM-family)

        def denoise(carry, step_idx):
            if use_fast:
                x, dpm_state, bank = carry
            else:
                x, dpm_state = carry
            t = ts[step_idx]
            prev_t = prev_ts[step_idx]

            def predict():
                tb = jnp.full((2 * bsz,), t, jnp.int32)
                pred = models.unet.apply({"params": params["unet"]},
                                         jnp.concatenate([x, x], axis=0), tb, ctx)
                pred_uncond, pred_cond = jnp.split(pred, 2, axis=0)
                return pred_uncond + guidance * (pred_cond - pred_uncond)

            if use_fast:
                pred, bank = fastsample.predict_or_reuse(
                    plan, step_idx, t, bank, cfg.fast.order, predict)
            else:
                pred = predict()
            force1 = jnp.logical_and(lower_order_final,
                                     step_idx == len(ts) - 1)
            x_new, dpm_new = scheduler_step(
                cfg.sampler, sched, pred, x, t, prev_t, dpm_state,
                force_first_order=force1,
                noise_key=jax.random.fold_in(ks, step_idx))
            if use_fast:
                return (x_new, dpm_new, bank), ()
            return (x_new, dpm_new), ()

        init = (x, S.dpm_init_state(x.shape))
        if use_fast:
            init = init + (fastsample.bank_init(x.shape),)
        (x, *_), _ = jax.lax.scan(denoise, init, jnp.arange(len(ts)))

        images = models.vae.apply({"params": params["vae"]}, x / scaling,
                                  method=models.vae.decode)
        return jnp.clip(images * 0.5 + 0.5, 0.0, 1.0)

    return jax.jit(sample_fn)

"""Host data loader: deterministic sampling plan + threaded prefetch.

Replaces the reference's torch DataLoader + WeightedRandomSampler stack
(diff_train.py:470-487) with a TPU-host-friendly design:

- a *sampling plan* is computed up front per (seed, epoch): weighted-with-
  replacement under dup regimes, shuffled otherwise — so every process knows
  the full global order and takes its own slice (no sampler state to sync);
- worker threads decode/augment (PIL releases the GIL for the heavy parts) into
  a bounded queue; batches are contiguous numpy, ready for shard_batch;
- iteration order is fully reproducible given (seed, epoch), including across
  restarts mid-epoch via `start_step`.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import numpy as np

from dcr_tpu.core import resilience as R
from dcr_tpu.core import tracing
from dcr_tpu.core.config import FaultToleranceConfig
from dcr_tpu.data import duplication as D
from dcr_tpu.data.dataset import ObjectAttributeDataset


class Batch(dict):
    """dict with attribute access: pixel_values [B,H,W,3], input_ids [B,L],
    index [B]."""

    __getattr__ = dict.__getitem__


class TooManyBadSamples(RuntimeError):
    """The epoch's quarantine budget (fault.max_bad_sample_frac) is spent."""


def sampling_plan(dataset: ObjectAttributeDataset, *, epoch: int,
                  seed: int) -> np.ndarray:
    """Global epoch order. Under dup_both/dup_image: weighted WITH replacement
    (the duplication mechanism itself — reference diff_train.py:470-479);
    otherwise a plain shuffle."""
    n = len(dataset)
    if dataset.cfg.duplication in ("dup_both", "dup_image"):
        weights = np.asarray(dataset.sampling_weights)[dataset.active_indices]
        return D.weighted_sample_indices(weights, n, seed, epoch)
    return D.shuffled_indices(n, seed, epoch)


class DataLoader:
    def __init__(self, dataset: ObjectAttributeDataset, *, batch_size: int,
                 num_workers: int = 8, seed: int = 0,
                 process_index: int = 0, process_count: int = 1,
                 drop_last: bool = True, prefetch: int = 4,
                 fault: Optional[FaultToleranceConfig] = None,
                 quarantine: Optional[R.QuarantineManifest] = None,
                 defer_budget_abort: bool = False):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.dataset = dataset
        self.global_batch_size = batch_size * process_count
        self.batch_size = batch_size
        self.num_workers = max(1, num_workers)
        self.seed = seed
        self.process_index = process_index
        self.process_count = process_count
        self.drop_last = drop_last
        self.prefetch = prefetch
        # fault=None (or max_bad_sample_frac=0) keeps the seed's fail-fast
        # contract: the first bad sample kills the epoch
        self.fault = fault
        self.quarantine = quarantine
        self.bad_samples = 0  # run-total, surfaced as faults/bad_samples
        self._bad_lock = threading.Lock()
        self._epoch_bad = [0]  # rebound per epoch(); read via epoch_bad_count
        # multi-host: a loader worker must NOT raise TooManyBadSamples
        # unilaterally — the budget is pod-global, and one host unwinding
        # while peers enter the next agreement round hangs the pod. The
        # trainer sets this on sliced multi-host loaders and aborts through
        # the fault-agreement word instead (bounded by one log window).
        self.defer_budget_abort = defer_budget_abort
        if len(dataset) < self.global_batch_size and drop_last:
            raise ValueError(
                f"dataset of {len(dataset)} samples can't fill one global batch "
                f"of {self.global_batch_size}")

    def steps_per_epoch(self) -> int:
        return len(self.dataset) // self.global_batch_size

    @property
    def epoch_bad_count(self) -> int:
        """Bad samples quarantined by THIS process in the current epoch —
        the local contribution to the pod-global budget agreement
        (core/coordination.py)."""
        return self._epoch_bad[0]

    def epoch_bad_budget(self) -> int:
        """The epoch's quarantine budget in samples, over the GLOBAL epoch
        (multi-host: hosts compare the summed count against this at agreement
        boundaries — per-host counts can each look fine while the pod as a
        whole is past the line)."""
        budget_frac = self.fault.max_bad_sample_frac if self.fault else 0.0
        return int(budget_frac * self.steps_per_epoch() * self.global_batch_size)

    def epoch(self, epoch: int, start_step: int = 0) -> Iterator[Batch]:
        """Yield this process's local batches for one epoch.

        Bad samples (decode failures after the dataset's own retries, or
        injected ``decode_error`` faults) are quarantined when
        ``fault.max_bad_sample_frac > 0``: the occurrence is replaced by a
        deterministic redraw from the same epoch plan (the next plan slot that
        decodes — the example another step would legitimately produce there,
        so the substitution is reproducible across restarts and processes),
        recorded in the quarantine manifest, and counted against the epoch's
        budget. Past the budget — or with the default budget of 0 — the error
        propagates to the consumer exactly as in the seed.
        """
        plan = sampling_plan(self.dataset, epoch=epoch, seed=self.seed)
        steps = self.steps_per_epoch()
        out_q: "queue.Queue[tuple[int, Optional[Batch], Optional[BaseException]]]" = (
            queue.Queue(maxsize=self.prefetch))
        stop = threading.Event()
        budget_frac = self.fault.max_bad_sample_frac if self.fault else 0.0
        epoch_budget = self.epoch_bad_budget()
        epoch_bad = [0]  # shared across workers, guarded by _bad_lock
        self._epoch_bad = epoch_bad  # published for the global-budget agreement

        def fetch(step: int, slot: int):
            from dcr_tpu.utils import faults

            position = int(plan[slot])
            # the `index` coordinate is the DATASET index — the same value the
            # quarantine manifest records for this occurrence
            if faults.fire("decode_error", step=step, slot=slot,
                           index=int(self.dataset.active_indices[position]),
                           epoch=epoch):
                raise faults.InjectedFault(
                    f"decode_error at epoch={epoch} step={step} slot={slot}")
            return self.dataset.get(position, epoch=epoch, slot=slot)

        def fetch_or_replace(step: int, slot: int):
            try:
                return fetch(step, slot)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as err:
                return self._replace(err, plan=plan, epoch=epoch, step=step,
                                     slot=slot, fetch=fetch,
                                     epoch_bad=epoch_bad,
                                     epoch_budget=epoch_budget,
                                     budget_frac=budget_frac)

        def make_batch(step: int) -> Batch:
            # one span per decoded batch, on the worker thread that built it:
            # the trace separates decode/augment work (here) from the train
            # thread's wait (train/data_wait) — the pair answers "is the host
            # keeping the chip fed"
            base = step * self.global_batch_size + self.process_index * self.batch_size
            with tracing.span("data/batch", step=step, epoch=epoch):
                examples = [fetch_or_replace(step, base + j)
                            for j in range(self.batch_size)]
                return Batch(
                    pixel_values=np.stack([e.pixel_values for e in examples]),
                    input_ids=np.stack([e.input_ids for e in examples]),
                    index=np.asarray([e.index for e in examples], np.int64),
                )

        def safe_put(item) -> bool:
            # never block forever: re-check stop so consumer-side teardown can't
            # leave producers pinned in put() holding decoded batches
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker(worker_id: int) -> None:
            for step in range(start_step + worker_id, steps, self.num_workers):
                if stop.is_set():
                    return
                try:
                    if not safe_put((step, make_batch(step), None)):
                        return
                except BaseException as e:  # surface decode errors to the consumer
                    safe_put((step, None, e))
                    return

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        pending: dict[int, Batch] = {}
        try:
            for step in range(start_step, steps):
                while step not in pending:
                    got_step, batch, err = out_q.get()
                    if err is not None:
                        raise err
                    pending[got_step] = batch
                yield pending.pop(step)
        finally:
            stop.set()
            # drain until every worker has exited (safe_put re-checks stop, so
            # this terminates promptly)
            for t in threads:
                while t.is_alive():
                    try:
                        out_q.get_nowait()
                    except queue.Empty:
                        t.join(timeout=0.05)

    def _replace(self, err: BaseException, *, plan: np.ndarray, epoch: int,
                 step: int, slot: int, fetch, epoch_bad: list,
                 epoch_budget: int, budget_frac: float):
        """Quarantine a bad occurrence and return its deterministic
        replacement, or re-raise when recovery is disabled / budget is spent.
        Thread-safe: loader workers hit this concurrently."""
        ds = self.dataset
        bad_position = int(plan[slot])
        bad_index = int(ds.active_indices[bad_position])
        if budget_frac <= 0:
            raise err  # seed behavior: no quarantine budget configured
        with self._bad_lock:
            epoch_bad[0] += 1
            self.bad_samples += 1
            n_bad = epoch_bad[0]
        if n_bad > epoch_budget and not self.defer_budget_abort:
            raise TooManyBadSamples(
                f"epoch {epoch}: {n_bad} bad samples exceed the quarantine "
                f"budget of {epoch_budget} (max_bad_sample_frac={budget_frac} "
                f"of {len(plan)} samples); last failure: {err!r}") from err
        # deterministic redraw from the SAME epoch plan: walk forward to the
        # next slot whose sample decodes — (epoch, slot) fully determine the
        # example, so every process/restart substitutes identically
        last: BaseException = err
        for k in range(1, len(plan)):
            cand = (slot + k) % len(plan)
            try:
                example = fetch(step, cand)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as cand_err:
                last = cand_err
                continue
            if self.quarantine is not None:
                self.quarantine.record(
                    "bad_sample", epoch=epoch, step=step, slot=slot,
                    index=bad_index, path=ds.paths[bad_index],
                    replacement_slot=cand,
                    replacement_index=int(ds.active_indices[int(plan[cand])]),
                    error=repr(err))
            else:
                R.log_event("bad_sample_replaced", epoch=epoch, step=step,
                            slot=slot, index=bad_index, replacement_slot=cand,
                            error=repr(err))
            return example
        raise TooManyBadSamples(
            f"epoch {epoch}: no decodable replacement found in the entire "
            f"plan ({len(plan)} slots); last failure: {last!r}") from err

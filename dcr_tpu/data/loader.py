"""Host data loader: deterministic sampling plan + threaded prefetch.

Replaces the reference's torch DataLoader + WeightedRandomSampler stack
(diff_train.py:470-487) with a TPU-host-friendly design:

- a *sampling plan* is computed up front per (seed, epoch): weighted-with-
  replacement under dup regimes, shuffled otherwise — so every process knows
  the full global order and takes its own slice (no sampler state to sync);
- worker threads decode/augment (PIL releases the GIL for the heavy parts) into
  a bounded queue; batches are contiguous numpy, ready for shard_batch;
- iteration order is fully reproducible given (seed, epoch), including across
  restarts mid-epoch via `start_step`.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import numpy as np

from dcr_tpu.data import duplication as D
from dcr_tpu.data.dataset import ObjectAttributeDataset


class Batch(dict):
    """dict with attribute access: pixel_values [B,H,W,3], input_ids [B,L],
    index [B]."""

    __getattr__ = dict.__getitem__


def sampling_plan(dataset: ObjectAttributeDataset, *, epoch: int,
                  seed: int) -> np.ndarray:
    """Global epoch order. Under dup_both/dup_image: weighted WITH replacement
    (the duplication mechanism itself — reference diff_train.py:470-479);
    otherwise a plain shuffle."""
    n = len(dataset)
    if dataset.cfg.duplication in ("dup_both", "dup_image"):
        weights = np.asarray(dataset.sampling_weights)[dataset.active_indices]
        return D.weighted_sample_indices(weights, n, seed, epoch)
    return D.shuffled_indices(n, seed, epoch)


class DataLoader:
    def __init__(self, dataset: ObjectAttributeDataset, *, batch_size: int,
                 num_workers: int = 8, seed: int = 0,
                 process_index: int = 0, process_count: int = 1,
                 drop_last: bool = True, prefetch: int = 4):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.dataset = dataset
        self.global_batch_size = batch_size * process_count
        self.batch_size = batch_size
        self.num_workers = max(1, num_workers)
        self.seed = seed
        self.process_index = process_index
        self.process_count = process_count
        self.drop_last = drop_last
        self.prefetch = prefetch
        if len(dataset) < self.global_batch_size and drop_last:
            raise ValueError(
                f"dataset of {len(dataset)} samples can't fill one global batch "
                f"of {self.global_batch_size}")

    def steps_per_epoch(self) -> int:
        return len(self.dataset) // self.global_batch_size

    def epoch(self, epoch: int, start_step: int = 0) -> Iterator[Batch]:
        """Yield this process's local batches for one epoch."""
        plan = sampling_plan(self.dataset, epoch=epoch, seed=self.seed)
        steps = self.steps_per_epoch()
        out_q: "queue.Queue[tuple[int, Optional[Batch], Optional[BaseException]]]" = (
            queue.Queue(maxsize=self.prefetch))
        stop = threading.Event()

        def make_batch(step: int) -> Batch:
            base = step * self.global_batch_size + self.process_index * self.batch_size
            positions = plan[base: base + self.batch_size]
            examples = [self.dataset.get(int(p), epoch=epoch, slot=base + j)
                        for j, p in enumerate(positions)]
            return Batch(
                pixel_values=np.stack([e.pixel_values for e in examples]),
                input_ids=np.stack([e.input_ids for e in examples]),
                index=np.asarray([e.index for e in examples], np.int64),
            )

        def safe_put(item) -> bool:
            # never block forever: re-check stop so consumer-side teardown can't
            # leave producers pinned in put() holding decoded batches
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker(worker_id: int) -> None:
            for step in range(start_step + worker_id, steps, self.num_workers):
                if stop.is_set():
                    return
                try:
                    if not safe_put((step, make_batch(step), None)):
                        return
                except BaseException as e:  # surface decode errors to the consumer
                    safe_put((step, None, e))
                    return

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()
        pending: dict[int, Batch] = {}
        try:
            for step in range(start_step, steps):
                while step not in pending:
                    got_step, batch, err = out_q.get()
                    if err is not None:
                        raise err
                    pending[got_step] = batch
                yield pending.pop(step)
        finally:
            stop.set()
            # drain until every worker has exited (safe_put re-checks stop, so
            # this terminates promptly)
            for t in threads:
                while t.is_alive():
                    try:
                        out_q.get_nowait()
                    except queue.Empty:
                        t.join(timeout=0.05)

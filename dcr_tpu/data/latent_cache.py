"""dcr-pipe: persistent latent cache — compute the frozen-encoder work once.

The paper's experiment matrix finetunes the SAME images under many
duplication/caption/mitigation regimes; every one of those runs re-pays the
frozen VAE encode and frozen text encode per step. ``dcr-precompute-latents``
(cli/precompute.py) runs the encode stage (diffusion/encode_stage.py,
``emit="moments"``) over a dataset ONCE and this module persists the result:

- per ACTIVE dataset index: the VAE posterior **moments** (mean, std — not
  a sample: the per-occurrence posterior draw stays a train-time decision
  keyed on the ``vae_sample`` RNG stream, so one cache serves every epoch
  and every duplication regime without freezing the latent noise) and the
  frozen text embedding (``ctx``) of that index's caption realization;
- a manifest keyed on a **fingerprint** of everything the latents depend
  on: VAE/text-encoder param digests, the dataset's path list, resolution /
  crop / caption regime, and the tokenizer — a cache built from different
  weights or a different dataset is *detected by key*, never trained on
  blind.

Verification discipline (the warmcache/copyrisk-dump pattern): every shard
is sha256-verified from bytes BEFORE ``np.load`` touches it and
sanity-checked (shapes, finiteness) after; a damaged shard is quarantined
out of the key space (``warmcache.quarantine_rename``), counted as a
``latentcache/*`` fault, and its indices simply become cache misses — the
producer's recompute path (encode_stage.cached_encode) re-encodes those
batches live. The ``latent_cache_corrupt@load=N`` fault kind
(utils/faults.py) damages the Nth shard read in memory so CI drives that
verify → quarantine → recompute path deterministically.

Layout::

    <dir>/manifest.json                 # fingerprint + shard shas
    <dir>/shard_00000.npz               # index/mean/std/ctx arrays
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from io import BytesIO
from pathlib import Path
from typing import Optional

import numpy as np

from dcr_tpu.core import fsio
from dcr_tpu.core import resilience as R
from dcr_tpu.core import tracing
from dcr_tpu.core.warmcache import quarantine_rename

CACHE_VERSION = 1
MANIFEST_NAME = "manifest.json"
DEFAULT_SHARD_SIZE = 512


class LatentCacheError(RuntimeError):
    """Typed: the cache directory cannot serve this run (absent manifest,
    fingerprint mismatch, or no readable shards). The caller decides whether
    that is fatal (training explicitly asked for a cache) or a degrade."""


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def params_digest(tree) -> str:
    """Content digest of a param pytree (path-ordered leaf bytes). The cache
    key half that says 'encoded with THESE frozen weights'."""
    import jax

    h = hashlib.sha256()
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in sorted(leaves, key=lambda kv: jax.tree_util.keystr(kv[0])):
        arr = np.asarray(jax.device_get(leaf))
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.dtype).encode() + str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def cache_fingerprint(cfg, dataset, tokenizer, *, vae_params,
                      text_params) -> dict:
    """Everything a cached latent/ctx depends on. Equal fingerprint <=> the
    cache holds exactly what this run's encoders would compute."""
    paths_sha = _sha("\n".join(
        dataset.paths[int(i)] for i in dataset.active_indices).encode())
    d = cfg.data
    m = cfg.model
    fp = {
        "version": CACHE_VERSION,
        "vae_sha": params_digest(vae_params),
        "text_sha": params_digest(text_params),
        "tokenizer": tokenizer.fingerprint(),
        "dataset_sha": paths_sha,
        "samples": int(len(dataset)),
        "data": {
            "resolution": d.resolution, "center_crop": d.center_crop,
            "random_flip": d.random_flip, "class_prompt": d.class_prompt,
            "instance_prompt": d.instance_prompt,
            "caption_jsons": list(d.caption_jsons),
            "rand_caption_tokens": d.rand_caption_tokens,
            "trainsubset": d.trainsubset, "seed": d.seed,
        },
        "model": {
            "sample_size": m.sample_size,
            "vae_block_out_channels": list(m.vae_block_out_channels),
            "vae_latent_channels": m.vae_latent_channels,
            "vae_scaling_factor": m.vae_scaling_factor,
            "text_hidden_size": m.text_hidden_size,
            "text_max_length": m.text_max_length,
            "mixed_precision": cfg.mixed_precision,
        },
    }
    # one JSON round-trip so the in-memory fingerprint is byte-equal to what
    # the manifest deserializes to (tuple->list etc.) — same discipline as
    # warmcache.program_fingerprint
    return json.loads(json.dumps(fp, sort_keys=True, default=str))


class LatentCacheWriter:
    """Accumulate encoded rows and persist shards + manifest atomically.

    Write order is shards first, manifest last (write-to-temp + rename), so
    a killed precompute leaves either a complete cache or no manifest —
    never a manifest naming shards that don't verify."""

    def __init__(self, cache_dir: str | Path, fingerprint: dict, *,
                 shard_size: Optional[int] = None):
        self.dir = Path(cache_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fingerprint = fingerprint
        # None -> the module default, resolved at call time so tests can
        # shrink shards through DEFAULT_SHARD_SIZE
        self.shard_size = max(1, shard_size or DEFAULT_SHARD_SIZE)
        self._rows: list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]] = []
        self._pending = 0
        self._shards: list[dict] = []
        self._total = 0

    def add(self, index: np.ndarray, mean: np.ndarray, std: np.ndarray,
            ctx: np.ndarray) -> None:
        index = np.asarray(index, np.int64)
        self._rows.append((index, np.asarray(mean, np.float32),
                           np.asarray(std, np.float32),
                           np.asarray(ctx, np.float32)))
        self._pending += len(index)
        while self._pending >= self.shard_size:
            self._flush_shard(self.shard_size)

    def _flush_shard(self, take: int) -> None:
        idx = np.concatenate([r[0] for r in self._rows])
        mean = np.concatenate([r[1] for r in self._rows])
        std = np.concatenate([r[2] for r in self._rows])
        ctx = np.concatenate([r[3] for r in self._rows])
        take = min(take, len(idx))
        buf = BytesIO()
        np.savez(buf, index=idx[:take], mean=mean[:take], std=std[:take],
                 ctx=ctx[:take])
        blob = buf.getvalue()
        name = f"shard_{len(self._shards):05d}.npz"
        path = self.dir / name
        tmp = path.with_name(f"{name}.tmp.{os.getpid()}")
        fsio.publish_durable(tmp, path, blob)
        self._shards.append({"file": name, "sha256": _sha(blob),
                             "count": int(take)})
        self._total += take
        rest = (idx[take:], mean[take:], std[take:], ctx[take:])
        self._rows = [rest] if len(rest[0]) else []
        self._pending = len(rest[0])

    def finalize(self) -> Path:
        """Flush the tail shard and commit the manifest."""
        while self._pending:
            self._flush_shard(self.shard_size)
        doc = {"version": CACHE_VERSION, "created_at": time.time(),
               "fingerprint": self.fingerprint, "total": self._total,
               "shards": self._shards}
        path = self.dir / MANIFEST_NAME
        tmp = path.with_name(f"{MANIFEST_NAME}.tmp.{os.getpid()}")
        # dir fsync: the manifest names the shards, so its rename must not
        # become durable while a shard's own rename is still volatile
        fsio.publish_durable(tmp, path,
                             json.dumps(doc, indent=1, sort_keys=True) + "\n",
                             sync_dir=True)
        tracing.event("latentcache/finalized", shards=len(self._shards),
                      rows=self._total)
        return path


class LatentCacheReader:
    """Verify-before-load reader with per-shard quarantine.

    Construction loads and verifies the whole cache: an unreadable/mismatched
    manifest raises :class:`LatentCacheError` (training explicitly asked for
    a cache that cannot serve it — silent slow fallback would mask the
    loss); a corrupt SHARD, by contrast, is quarantined and its indices
    degrade to recompute misses, because losing one shard of a valid cache
    must not forfeit the other 95% of the win.
    """

    def __init__(self, cache_dir: str | Path,
                 expected_fingerprint: Optional[dict] = None):
        self.dir = Path(cache_dir)
        self._load_seq = 0
        manifest = self._read_manifest()
        if expected_fingerprint is not None and \
                manifest["fingerprint"] != expected_fingerprint:
            diffs = _fingerprint_diff(manifest["fingerprint"],
                                      expected_fingerprint)
            R.bump_counter("latentcache/fingerprint_mismatch")
            raise LatentCacheError(
                f"latent cache {self.dir} was built for a different "
                f"run: fingerprint differs at {diffs} — re-run "
                "dcr-precompute-latents for this config/weights")
        self.fingerprint = manifest["fingerprint"]
        self.total = int(manifest.get("total", 0))
        # per-shard arrays, never concatenated: lookup() gathers rows
        # through an index -> (shard, row) map, so peak host memory is the
        # verified shards themselves — no monolithic second copy
        self._row_of: dict[int, tuple[int, int]] = {}
        self._shards: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        for shard in manifest["shards"]:
            arrays = self._load_shard(shard)
            if arrays is None:
                continue
            idx, mean, std, ctx = arrays
            si = len(self._shards)
            for j, i in enumerate(idx):
                self._row_of[int(i)] = (si, j)
            self._shards.append((mean, std, ctx))
        if not self._shards:
            raise LatentCacheError(
                f"latent cache {self.dir}: no shard survived verification "
                f"({len(manifest['shards'])} listed)")
        self.cached = len(self._row_of)
        tracing.event("latentcache/loaded", rows=self.cached,
                      total=self.total, shards=len(self._shards))

    # -- verification --------------------------------------------------------

    def _read_manifest(self) -> dict:
        path = self.dir / MANIFEST_NAME
        try:
            raw = R.read_bytes_with_retry(path, name="latent_cache_manifest")
        except FileNotFoundError:
            raise LatentCacheError(
                f"latent cache {self.dir} has no {MANIFEST_NAME} — run "
                "dcr-precompute-latents first") from None
        except OSError as e:
            raise LatentCacheError(
                f"latent cache manifest unreadable: {e!r}") from e
        try:
            doc = json.loads(raw.decode("utf-8"))
            if not isinstance(doc.get("shards"), list) or \
                    "fingerprint" not in doc:
                raise ValueError("manifest missing shards/fingerprint")
            return doc
        except (UnicodeDecodeError, ValueError) as e:
            dest = quarantine_rename(path)
            R.log_event("latent_cache_manifest_corrupt", error=repr(e),
                        path=str(path),
                        quarantined_to=str(dest) if dest else None)
            R.bump_counter("latentcache/manifest_corrupt")
            raise LatentCacheError(
                f"latent cache manifest corrupt ({e}); quarantined — re-run "
                "dcr-precompute-latents") from e

    def _load_shard(self, shard: dict):
        from dcr_tpu.utils import faults

        path = self.dir / str(shard.get("file", ""))
        try:
            blob = R.read_bytes_with_retry(path, name="latent_cache_shard")
        except (FileNotFoundError, OSError) as e:
            self._quarantine(path, "shard_missing", repr(e), rename=False)
            return None
        seq = self._load_seq
        self._load_seq += 1
        if faults.fire("latent_cache_corrupt", load=seq):
            # deterministic CI poisoning: damage the blob in memory so the
            # REAL verify/quarantine/recompute path runs end to end
            mid = len(blob) // 2
            blob = blob[:mid] + bytes([blob[mid] ^ 0xFF]) + blob[mid + 1:] \
                if blob else b""
        if _sha(blob) != shard.get("sha256"):
            self._quarantine(path, "shard_corrupt", "sha256 mismatch")
            return None
        try:
            with np.load(BytesIO(blob)) as z:
                idx = np.asarray(z["index"], np.int64)
                mean, std, ctx = (np.asarray(z[k], np.float32)
                                  for k in ("mean", "std", "ctx"))
        except Exception as e:
            self._quarantine(path, "shard_corrupt", f"unreadable npz: {e!r}")
            return None
        n = len(idx)
        if not (len(mean) == len(std) == len(ctx) == n == shard.get("count")):
            self._quarantine(path, "shard_corrupt", "row-count mismatch")
            return None
        if not (np.isfinite(mean).all() and np.isfinite(std).all()
                and np.isfinite(ctx).all()):
            self._quarantine(path, "shard_corrupt", "non-finite values")
            return None
        return idx, mean, std, ctx

    def _quarantine(self, path: Path, kind: str, detail: str,
                    rename: bool = True) -> None:
        dest = quarantine_rename(path) if rename else None
        R.log_event("latent_cache_quarantined", kind=kind, detail=detail,
                    shard=str(path),
                    quarantined_to=str(dest) if dest else None)
        R.bump_counter(f"latentcache/{kind}")

    # -- serving -------------------------------------------------------------

    def lookup(self, indices: np.ndarray):
        """(mean, std, ctx) batch rows for ``indices``, or None when any
        index is uncached (the caller re-encodes that batch live)."""
        rows = []
        for i in np.asarray(indices):
            row = self._row_of.get(int(i))
            if row is None:
                return None
            rows.append(row)
        gathered = [self._shards[si] for si, _ in rows]
        return tuple(
            np.stack([shard[f][rj] for shard, (_, rj) in zip(gathered, rows)])
            for f in range(3))

    def coverage(self) -> tuple[int, int]:
        """(indices served from cache, indices the manifest promised)."""
        return self.cached, self.total


def _fingerprint_diff(a: dict, b: dict, prefix: str = "") -> list[str]:
    """Dotted paths where two fingerprints differ (readable errors)."""
    diffs: list[str] = []
    for key in sorted(set(a) | set(b)):
        va, vb = a.get(key), b.get(key)
        path = f"{prefix}{key}"
        if isinstance(va, dict) and isinstance(vb, dict):
            diffs.extend(_fingerprint_diff(va, vb, prefix=f"{path}."))
        elif va != vb:
            diffs.append(path)
    return diffs[:10]

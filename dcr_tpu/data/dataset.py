"""Image-folder dataset with caption conditioning and duplication regimes.

Capability-equivalent of the reference's ObjectAttributeDataset
(datasets.py:32-152): class-subdirectory image folder, resize→crop→flip→
normalize to [-1, 1], caption assignment per regime, cached duplication
weights, CLIP tokenization to fixed length. Host-side (numpy/PIL) — device
work stays in jit; every random decision derives from (seed, epoch, index) so
any sample is recomputable on any worker.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

import numpy as np
from PIL import Image

from dcr_tpu.core import resilience as R
from dcr_tpu.core.config import DataConfig, FaultToleranceConfig
from dcr_tpu.core.rng import host_python_rng
from dcr_tpu.data import captions as C
from dcr_tpu.data import duplication as D
from dcr_tpu.data.tokenizer import TokenizerBase

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".webp", ".ppm", ".tif", ".tiff")


class SampleDecodeError(RuntimeError):
    """A sample failed to decode after all retry attempts. Carries enough
    context for the loader's quarantine manifest."""

    def __init__(self, index: int, path: str, cause: BaseException):
        super().__init__(f"sample {index} ({path}) failed to decode: {cause!r}")
        self.index = index
        self.path = path
        self.cause = cause


def list_image_folder(root: str | Path) -> tuple[list[str], list[int], list[str]]:
    """(paths, labels, classnames) from a class-per-subdirectory layout, sorted
    deterministically (same contract as torchvision ImageFolder)."""
    root = Path(root)
    classes = sorted(d.name for d in root.iterdir() if d.is_dir())
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {root}")
    paths: list[str] = []
    labels: list[int] = []
    for li, cls in enumerate(classes):
        for p in sorted((root / cls).rglob("*")):
            if p.suffix.lower() in IMG_EXTENSIONS:
                paths.append(str(p))
                labels.append(li)
    if not paths:
        raise FileNotFoundError(f"no images under {root}")
    return paths, labels, classes


def _resize_shorter_side(img: Image.Image, size: int) -> Image.Image:
    w, h = img.size
    if w <= h:
        nw, nh = size, max(size, round(h * size / w))
    else:
        nw, nh = max(size, round(w * size / h)), size
    return img.resize((nw, nh), Image.BILINEAR)


def _open_image(path: str, size: int) -> Image.Image:
    """PIL image, using the native libjpeg scaled-decode fast path for JPEGs
    (decodes at a reduced DCT scale >= the target size; dramatically cheaper
    than full decode for large photos)."""
    if Path(path).suffix.lower() in (".jpg", ".jpeg"):
        try:
            from dcr_tpu.native import jpeg_decoder

            if jpeg_decoder.available():  # avoid double-read when no fast path
                arr = jpeg_decoder.decode_scaled(Path(path).read_bytes(), size)
                if arr is not None:
                    return Image.fromarray(arr)
        except Exception as e:
            # fall back to the full PIL decode below, but never silently: a
            # systematic fast-path failure (bad libjpeg build, corrupt shard)
            # must show up in the faults/ telemetry, not as a 10x slowdown
            from dcr_tpu.core import resilience as R

            R.log_event("jpeg_fast_path_error", path=str(path), error=repr(e))
            R.bump_counter("jpeg_fast_path_errors")
    with Image.open(path) as img:
        return img.convert("RGB").copy()


def load_and_transform(path: str, size: int, *, center_crop: bool,
                       random_flip: bool, rng: np.random.Generator) -> np.ndarray:
    """Decode + resize(shorter side)→crop→flip→normalize to [-1,1] NHWC f32
    (reference transform stack, datasets.py:59-67)."""
    img = _open_image(path, size)
    img = _resize_shorter_side(img, size)
    w, h = img.size
    if center_crop:
        left, top = (w - size) // 2, (h - size) // 2
    else:
        left = int(rng.integers(0, w - size + 1))
        top = int(rng.integers(0, h - size + 1))
    img = img.crop((left, top, left + size, top + size))
    arr = np.asarray(img, np.float32) / 255.0
    if random_flip and rng.uniform() < 0.5:
        arr = arr[:, ::-1, :]
    return arr * 2.0 - 1.0


@dataclass
class Example:
    pixel_values: np.ndarray  # [H, W, 3] f32 in [-1, 1]
    input_ids: np.ndarray     # [max_length] int32
    index: int
    caption: str


class ObjectAttributeDataset:
    """Deterministic map-style dataset over an image folder."""

    def __init__(self, cfg: DataConfig, tokenizer: TokenizerBase,
                 caption_tables: Optional[dict] = None,
                 fault: Optional[FaultToleranceConfig] = None):
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.fault = fault or FaultToleranceConfig()
        self.paths, self.labels, self.classes = list_image_folder(cfg.train_data_dir)
        # classnames: Imagenette convention when recognizable, else folder names
        if any(s in str(cfg.train_data_dir) for s in ("imagenette", "Imagenette")):
            self.classnames = list(C.get_classnames(cfg.train_data_dir))
        else:
            self.classnames = self.classes
        self.prompts = caption_tables
        if self.prompts is None and cfg.caption_jsons:
            self.prompts = {}
            for j in cfg.caption_jsons:
                self.prompts.update(json.loads(R.read_text_with_retry(
                    j, attempts=self.fault.io_retries, name=f"captions:{j}")))
        needs_prompts = cfg.class_prompt.startswith("instancelevel") or (
            cfg.trainspecial not in (None, "none"))
        if needs_prompts and not self.prompts:
            raise ValueError(
                f"class_prompt={cfg.class_prompt!r}/trainspecial={cfg.trainspecial!r} "
                "need caption tables (data.caption_jsons)")
        if cfg.duplication in ("dup_both", "dup_image"):
            self.sampling_weights = D.load_or_create_weights(
                cfg.train_data_dir, len(self.paths), cfg.weight_pc,
                cfg.dup_weight, cfg.seed)
        else:
            self.sampling_weights = np.ones(len(self.paths), np.int64)
        self.spec = C.CaptionSpec(
            class_prompt=cfg.class_prompt,
            duplication=cfg.duplication,
            instance_prompt=cfg.instance_prompt,
            trainspecial=cfg.trainspecial,
            trainspecial_prob=cfg.trainspecial_prob,
        )
        # partial-data training (reference --trainsubset via Subset,
        # diff_train.py:264-266,466-468): restrict to the first N indices
        self.active_indices = np.arange(len(self.paths))
        if cfg.trainsubset and cfg.trainsubset > 0:
            self.active_indices = self.active_indices[: cfg.trainsubset]

    def __len__(self) -> int:
        return len(self.active_indices)

    def get(self, position: int, epoch: int = 0,
            slot: Optional[int] = None) -> Example:
        """position indexes the (possibly subset) dataset; (epoch, slot) feed the
        rng. slot is the occurrence's place in the epoch's sampling plan — under
        weighted sampling with replacement the same image appears at several
        slots and each occurrence must redraw crop/flip/caption independently
        (the reference redraws per __getitem__; dup_image's 'same image,
        different captions' depends on it). Defaults to position for direct use."""
        index = int(self.active_indices[position])
        slot = position if slot is None else slot

        def build() -> Example:
            # a fresh rng per attempt: a retried decode must produce the
            # byte-identical example a first-try success would have
            rng = host_python_rng(self.cfg.seed, f"sample_e{epoch}_s{slot}_i{index}")
            pixels = load_and_transform(
                self.paths[index], self.cfg.resolution,
                center_crop=self.cfg.center_crop,
                random_flip=self.cfg.random_flip, rng=rng)
            caption = C.assign_caption(
                self.spec, path=self.paths[index], label=self.labels[index],
                classnames=self.classnames, prompts=self.prompts,
                sampling_weight=float(self.sampling_weights[index]),
                tokenizer=self.tokenizer, rng=rng)
            ids = self.tokenizer(caption)[0]
            return Example(pixel_values=pixels, input_ids=ids, index=index,
                           caption=caption)

        ft = self.fault
        try:
            # retry transient AND deterministic decode errors alike: one spare
            # attempt is cheap, and a truly-corrupt file fails identically and
            # escalates to SampleDecodeError for the loader's quarantine
            return R.retry_call(build, attempts=1 + max(0, ft.decode_retries),
                                base_delay=ft.retry_base_delay,
                                max_delay=ft.retry_max_delay,
                                retry_on=(Exception,),
                                name=f"decode:{Path(self.paths[index]).name}")
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:
            raise SampleDecodeError(index, self.paths[index], e) from e

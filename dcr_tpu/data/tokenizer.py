"""Text tokenization for conditioning.

The reference passes around a HF CLIPTokenizer (diff_train.py:370-374,
datasets.py:144-150: truncation + pad-to-max-length 77, and decode of random
token-id lists for instancelevel_random captions, datasets.py:140-142).

Two implementations behind one interface:

- :class:`ClipBPETokenizer` — a faithful CLIP byte-pair-encoding tokenizer given
  local ``vocab.json``/``merges.txt`` files (no network in this environment, so
  the files must be provided, e.g. exported once from an SD checkpoint dir).
- :class:`HashTokenizer` — deterministic hashing tokenizer for tests/smoke runs:
  stable word→id mapping, reversible enough for the random-caption decode path.
"""

from __future__ import annotations

import gzip
import hashlib
import html
import json
import re
from functools import lru_cache
from pathlib import Path
from typing import Sequence

import numpy as np

from dcr_tpu.core import resilience as R


class TokenizerBase:
    vocab_size: int
    model_max_length: int
    bos_token_id: int
    eos_token_id: int
    pad_token_id: int

    def encode(self, text: str) -> list[int]:
        raise NotImplementedError

    def decode(self, ids: Sequence[int]) -> str:
        raise NotImplementedError

    def _fingerprint_fields(self) -> dict:
        """Everything that determines the text -> ids mapping; subclasses add
        their vocab content. Must be JSON-serializable and order-stable."""
        return {"class": type(self).__name__, "vocab_size": self.vocab_size,
                "model_max_length": self.model_max_length,
                "bos": self.bos_token_id, "eos": self.eos_token_id,
                "pad": self.pad_token_id}

    def fingerprint(self) -> str:
        """Stable hex id of this tokenizer's text->ids mapping. Two tokenizers
        with the same fingerprint produce identical ids for identical text —
        the cache-key component the serve embedding cache (dcr_tpu/serve/)
        needs so a checkpoint swap can never serve stale embeddings."""
        payload = json.dumps(self._fingerprint_fields(), sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    def __call__(self, texts: str | Sequence[str],
                 max_length: int | None = None) -> np.ndarray:
        """Tokenize with truncation + pad-to-max-length (reference
        datasets.py:144-150). Returns int32 [B, max_length]."""
        if isinstance(texts, str):
            texts = [texts]
        max_length = max_length or self.model_max_length
        out = np.full((len(texts), max_length), self.pad_token_id, np.int32)
        for i, text in enumerate(texts):
            ids = [self.bos_token_id] + self.encode(text)[: max_length - 2] + [self.eos_token_id]
            out[i, : len(ids)] = ids
        return out


# ---------------------------------------------------------------------------
# CLIP BPE (loads the standard vocab/merges files when available locally)
# ---------------------------------------------------------------------------

@lru_cache()
def _bytes_to_unicode() -> dict[int, str]:
    bs = (list(range(ord("!"), ord("~") + 1)) + list(range(ord("¡"), ord("¬") + 1))
          + list(range(ord("®"), ord("ÿ") + 1)))
    cs = bs[:]
    n = 0
    for b in range(2 ** 8):
        if b not in bs:
            bs.append(b)
            cs.append(2 ** 8 + n)
            n += 1
    return dict(zip(bs, [chr(c) for c in cs]))


def _basic_clean(text: str) -> str:
    text = html.unescape(html.unescape(text))
    return text.strip()


def _whitespace_clean(text: str) -> str:
    return re.sub(r"\s+", " ", text).strip()


class ClipBPETokenizer(TokenizerBase):
    """CLIP's BPE with end-of-word '</w>' markers, vocab 49408, context 77."""

    # ASCII approximation of CLIP's \p{L}/\p{N} pattern (stdlib `re` has no
    # unicode property classes; non-ASCII text falls through to the byte tokens)
    PAT = re.compile(
        r"<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d|[a-zA-Z]+|[0-9]|[^\sa-zA-Z0-9]+",
        re.IGNORECASE,
    )

    def __init__(self, vocab_path: str | Path, merges_path: str | Path,
                 model_max_length: int = 77):
        vocab_path, merges_path = Path(vocab_path), Path(merges_path)
        # kept so trainers can republish the files into their output dir
        # (the diffusers `tokenizer/` subfolder contract)
        self.vocab_path, self.merges_path = vocab_path, merges_path
        # vocab/merges live on network filesystems in pod runs; transient
        # read errors are retried (core/resilience.py), missing files are not
        self.encoder: dict[str, int] = json.loads(
            R.read_text_with_retry(vocab_path, name=f"vocab:{vocab_path.name}"))
        merges_raw = R.read_bytes_with_retry(merges_path,
                                             name=f"merges:{merges_path.name}")
        merges_text = (gzip.decompress(merges_raw).decode("utf-8")
                       if merges_path.suffix == ".gz"
                       else merges_raw.decode("utf-8"))
        lines = merges_text.split("\n")
        if lines and lines[0].startswith("#"):
            lines = lines[1:]
        merges = [tuple(m.split()) for m in lines if len(m.split()) == 2]
        self.bpe_ranks = dict(zip(merges, range(len(merges))))
        self.byte_encoder = _bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self.decoder = {v: k for k, v in self.encoder.items()}
        self.vocab_size = len(self.encoder)
        self.model_max_length = model_max_length
        self.bos_token_id = self.encoder.get("<|startoftext|>", self.vocab_size - 2)
        self.eos_token_id = self.encoder.get("<|endoftext|>", self.vocab_size - 1)
        self.pad_token_id = self.eos_token_id  # CLIP pads with EOT
        self._bpe_cache: dict[str, str] = {}

    def _fingerprint_fields(self) -> dict:
        d = super()._fingerprint_fields()
        h = hashlib.sha256()
        for tok, idx in sorted(self.encoder.items(), key=lambda kv: kv[1]):
            h.update(f"{tok}\x00{idx}\x01".encode())
        for (a, b), rank in sorted(self.bpe_ranks.items(), key=lambda kv: kv[1]):
            h.update(f"{a}\x00{b}\x00{rank}\x01".encode())
        d["vocab_sha"] = h.hexdigest()
        return d

    def _bpe(self, token: str) -> str:
        if token in self._bpe_cache:
            return self._bpe_cache[token]
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
        if not pairs:
            return token + "</w>"
        while True:
            bigram = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if bigram not in self.bpe_ranks:
                break
            first, second = bigram
            new_word: list[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(first, i)
                except ValueError:
                    new_word.extend(word[i:])
                    break
                new_word.extend(word[i:j])
                i = j
                if i < len(word) - 1 and word[i] == first and word[i + 1] == second:
                    new_word.append(first + second)
                    i += 2
                else:
                    new_word.append(word[i])
                    i += 1
            word = tuple(new_word)
            if len(word) == 1:
                break
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
        out = " ".join(word)
        self._bpe_cache[token] = out
        return out

    def encode(self, text: str) -> list[int]:
        ids: list[int] = []
        text = _whitespace_clean(_basic_clean(text)).lower()
        for token in re.findall(self.PAT, text):
            token_bytes = "".join(self.byte_encoder[b] for b in token.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self._bpe(token_bytes).split(" ")
                       if t in self.encoder)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        text = "".join(self.decoder.get(int(i), "") for i in ids)
        raw = bytearray(self.byte_decoder.get(c, 32) for c in text)
        text = raw.decode("utf-8", errors="replace").replace("</w>", " ")
        for special in ("<|startoftext|>", "<|endoftext|>"):
            text = text.replace(special, "")
        return text.strip()


# ---------------------------------------------------------------------------
# Hash tokenizer (offline fallback, deterministic)
# ---------------------------------------------------------------------------

class HashTokenizer(TokenizerBase):
    """Deterministic word-hash tokenizer. Not linguistically meaningful, but
    stable across runs/processes, reversible for ids it produced (keeps the
    instancelevel_random decode→re-encode loop consistent), and adequate for
    tests and CPU smoke training."""

    def __init__(self, vocab_size: int = 49408, model_max_length: int = 77):
        self.vocab_size = vocab_size
        self.model_max_length = model_max_length
        self.bos_token_id = vocab_size - 2
        self.eos_token_id = vocab_size - 1
        self.pad_token_id = 0
        self._reserved = {0, self.bos_token_id, self.eos_token_id}
        self._id_to_word: dict[int, str] = {}

    def _word_id(self, word: str) -> int:
        h = int.from_bytes(hashlib.sha256(word.lower().encode()).digest()[:8], "little")
        wid = 1 + h % (self.vocab_size - 3)  # skip pad/bos/eos
        self._id_to_word.setdefault(wid, word.lower())
        return wid

    def encode(self, text: str) -> list[int]:
        return [self._word_id(w) for w in _whitespace_clean(text).split(" ") if w]

    def decode(self, ids: Sequence[int]) -> str:
        words = []
        for i in ids:
            i = int(i)
            if i in self._reserved:
                continue
            words.append(self._id_to_word.get(i, f"tok{i}"))
        return " ".join(words)


def load_tokenizer(checkpoint_dir: str | Path | None = None,
                   vocab_size: int = 49408,
                   model_max_length: int = 77) -> TokenizerBase:
    """ClipBPETokenizer when vocab/merges files are present, else HashTokenizer."""
    if checkpoint_dir:
        d = Path(checkpoint_dir)
        for sub in (d, d / "tokenizer"):
            vocab, merges = sub / "vocab.json", sub / "merges.txt"
            if vocab.exists() and merges.exists():
                return ClipBPETokenizer(vocab, merges, model_max_length)
    return HashTokenizer(vocab_size, model_max_length)

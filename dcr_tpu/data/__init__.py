"""L2: datasets, captions, duplication weights, tokenization, host data loading."""

"""Data-duplication regimes: per-sample sampling weights with on-disk caching.

Reproduces the semantics of the reference's weight machinery
(datasets.py:76-90): under ``dup_both``/``dup_image`` a random ``weight_pc``
fraction of samples gets weight ``dup_weight`` (others 1), cached to a pickle
keyed by (weight_pc, dup_weight, seed) next to the data so train and eval see
the same assignment (eval reads it for the duplicated-vs-not analysis,
diff_retrieval.py:561-583). File name and pickle format match the reference so
the two toolchains interoperate on the same dataset directory.
"""

from __future__ import annotations

import pickle
from pathlib import Path
from typing import Sequence

import numpy as np

from dcr_tpu.core.rng import host_python_rng


def weights_cache_path(data_root: str | Path, weight_pc: float, dup_weight: int,
                       seed: int) -> Path:
    # same naming convention as the reference (datasets.py:77)
    return Path(data_root) / f"weights_{weight_pc}_{dup_weight}_seed{seed}.pickle"


def make_sampling_weights(num_samples: int, weight_pc: float, dup_weight: int,
                          seed: int) -> np.ndarray:
    """weight_pc fraction of samples get integer weight dup_weight, rest 1."""
    weights = np.ones(num_samples, np.int64)
    rng = host_python_rng(seed, "dup_weights")
    chosen = rng.choice(num_samples, int(weight_pc * num_samples), replace=False)
    weights[chosen] = int(dup_weight)
    return weights


def load_or_create_weights(data_root: str | Path, num_samples: int,
                           weight_pc: float, dup_weight: int,
                           seed: int) -> np.ndarray:
    path = weights_cache_path(data_root, weight_pc, dup_weight, seed)
    if path.exists():
        with open(path, "rb") as f:
            weights = np.asarray(pickle.load(f))
        if len(weights) != num_samples:
            raise ValueError(
                f"cached weights at {path} cover {len(weights)} samples, "
                f"dataset has {num_samples}; delete the stale cache or fix the data dir")
        return weights
    weights = make_sampling_weights(num_samples, weight_pc, dup_weight, seed)
    with open(path, "wb") as f:
        pickle.dump(weights.tolist(), f, protocol=pickle.HIGHEST_PROTOCOL)
    return weights


def weighted_sample_indices(weights: Sequence[float], num_draws: int,
                            seed: int, epoch: int) -> np.ndarray:
    """Weighted sampling WITH replacement (the reference's WeightedRandomSampler,
    diff_train.py:470-479), deterministic per (seed, epoch)."""
    weights = np.asarray(weights, np.float64)
    p = weights / weights.sum()
    rng = host_python_rng(seed, f"weighted_sampler_epoch{epoch}")
    return rng.choice(len(weights), size=num_draws, replace=True, p=p)


def shuffled_indices(num_samples: int, seed: int, epoch: int) -> np.ndarray:
    rng = host_python_rng(seed, f"shuffle_epoch{epoch}")
    return rng.permutation(num_samples)

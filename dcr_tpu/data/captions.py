"""Caption assignment per conditioning regime + train-time caption mitigations.

Behavioral port of the reference's caption logic (datasets.py:100-142), with the
global-RNG calls replaced by an explicit per-sample ``np.random.Generator`` so
results are reproducible and independent of worker scheduling (SURVEY.md §7.3).

Conditioning regimes (diff_train.py:90-96):
  nolevel               constant prompt ("An image")
  classlevel            "An image of {classname}"
  instancelevel_blip    per-image BLIP caption list (json), first entry
  instancelevel_ogcap   per-image original caption (json)
  instancelevel_random  caption stored as a token-id list, decoded via tokenizer

Duplication interplay (datasets.py:133-139): under dup_image, duplicated samples
(weight > 1) draw a random caption from the image's list instead of the first —
that's what makes dup_image "same image, different captions".

Train-time mitigations (datasets.py:100-125, arXiv:2305.20086 §5):
  allcaps      always sample a random caption from the image's list
  randrepl     with prob p replace the whole caption by 4 random tokens, decoded
  randwordadd  with prob p insert 2 random-token words at random positions
  wordrepeat   with prob p re-insert 2 words already present at random positions
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Mapping, Optional, Sequence

import numpy as np

from dcr_tpu.data.tokenizer import TokenizerBase

# Imagenette class names (reference datasets.py:25-29)
IMAGENETTE_CLASSES = (
    "tench", "English springer", "cassette player", "chain saw", "church",
    "French horn", "garbage truck", "gas pump", "golf ball", "parachute",
)
IMAGENETTE_2CLASS = ("church", "garbage truck")


def get_classnames(dataset_path: str) -> tuple[str, ...]:
    if "imagenette_2class" in str(dataset_path):
        return IMAGENETTE_2CLASS
    return IMAGENETTE_CLASSES


def insert_rand_word(sentence: str, word: str, rng: np.random.Generator) -> str:
    """Insert `word` at a random position (reference datasets.py:154-159)."""
    words = sentence.split(" ")
    pos = int(rng.integers(0, len(words) + 1))
    words.insert(pos, word)
    return " ".join(words)


@dataclass(frozen=True)
class CaptionSpec:
    class_prompt: str                      # conditioning regime
    duplication: str = "nodup"
    instance_prompt: str = "An image"      # nolevel text
    trainspecial: Optional[str] = None     # mitigation or None/"none"
    trainspecial_prob: float = 0.1
    rand_token_high: int = 49400           # reference uses randint(49400)


def assign_caption(spec: CaptionSpec, *, path: str, label: int,
                   classnames: Sequence[str],
                   prompts: Optional[Mapping[str, Sequence[str]]],
                   sampling_weight: float,
                   tokenizer: TokenizerBase,
                   rng: np.random.Generator) -> str:
    """Produce the training caption for one sample (pure given rng state)."""
    special = spec.trainspecial if spec.trainspecial not in (None, "none") else None
    if special is not None:
        caps = prompts[path]
        if special == "allcaps":
            return str(caps[int(rng.integers(0, len(caps)))])
        caption = str(caps[0])
        if float(rng.uniform()) <= spec.trainspecial_prob:
            if special == "randrepl":
                ids = [int(i) for i in rng.integers(0, spec.rand_token_high, size=4)]
                return tokenizer.decode(ids)
            if special == "randwordadd":
                for _ in range(2):
                    word = tokenizer.decode(
                        [int(rng.integers(0, spec.rand_token_high))])
                    caption = insert_rand_word(caption, word, rng)
                return caption
            if special == "wordrepeat":
                words = caption.split(" ")
                for _ in range(2):
                    word = str(words[int(rng.integers(0, len(words)))])
                    caption = insert_rand_word(caption, word, rng)
                return caption
            raise ValueError(f"unknown trainspecial {special!r}")
        return caption

    if spec.class_prompt == "nolevel":
        return spec.instance_prompt
    if spec.class_prompt == "classlevel":
        return f"An image of {classnames[label]}"
    if spec.class_prompt in ("instancelevel_blip", "instancelevel_random",
                             "instancelevel_ogcap"):
        caps = prompts[path]
        if spec.duplication == "dup_image" and sampling_weight > 1:
            caption = str(caps[int(rng.integers(0, len(caps)))])
        else:
            caption = str(caps[0])
        if spec.class_prompt == "instancelevel_random":
            # stored as a literal token-id list; decode through the tokenizer
            # (reference datasets.py:140-142)
            ids = ast.literal_eval(caption) if isinstance(caption, str) else caption
            caption = tokenizer.decode([int(i) for i in ids])
        return caption
    raise ValueError(f"unknown class_prompt {spec.class_prompt!r}")

"""LRU prompt-embedding cache.

The CLIP text tower is the only per-prompt compute in the serving path whose
result is reusable verbatim: a prompt's clean (pre-mitigation-noise) embedding
depends on nothing but the tokenizer's text->ids mapping and the text-encoder
weights. Production prompt streams are heavily repetitive, so caching the
[L, D] embedding on host memory turns the text tower into a dict lookup for
repeats while the UNet scan — the real work — still runs per request.

Key discipline (:func:`embedding_key`): the key binds the tokenizer
fingerprint (checkpoint swap => different fingerprint => no stale hits) and
the mitigation parameters. Per-request mitigation NOISE is *not* cached — it
is applied inside the jitted sampler from each request's own PRNG key — but
keying on the mitigation keeps entries from different serving configurations
from aliasing, so flipping ``rand_noise_lam`` mid-fleet can never replay
another configuration's entries.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from dcr_tpu.serve.queue import GenBucket


def mitigation_tag(bucket: GenBucket) -> str:
    """Canonical string of the bucket's embedding-affecting mitigation params."""
    return f"lam={bucket.rand_noise_lam:g}"


def embedding_key(tokenizer_fp: str, prompt: str, mitigation: str) -> tuple:
    """(tokenizer fingerprint, prompt, mitigation params) — the full identity
    of a cached embedding."""
    return (tokenizer_fp, prompt, mitigation)


class EmbeddingCache:
    """Thread-safe LRU of host numpy embeddings with hit/miss counters.

    ``capacity == 0`` disables caching (every get misses, puts drop) — the
    knob for memory-constrained deployments. Values live on HOST memory, so
    cache size never competes with the sampler for device HBM; the worker
    pays one host->device transfer per batch either way.
    """

    def __init__(self, capacity: int):
        if capacity < 0:
            raise ValueError(f"cache capacity must be >= 0, got {capacity}")
        self.capacity = capacity
        self._od: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> Optional[np.ndarray]:
        with self._lock:
            if key in self._od:
                self._od.move_to_end(key)
                self.hits += 1
                return self._od[key]
            self.misses += 1
            return None

    def put(self, key: tuple, value: np.ndarray) -> None:
        if self.capacity == 0:
            return
        with self._lock:
            self._od[key] = value
            self._od.move_to_end(key)
            while len(self._od) > self.capacity:
                self._od.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def __contains__(self, key: tuple) -> bool:
        """Membership probe WITHOUT touching recency or counters (tests)."""
        with self._lock:
            return key in self._od

    def stats(self) -> dict:
        with self._lock:
            hits, misses, size = self.hits, self.misses, len(self._od)
        total = hits + misses
        return {"hits": hits, "misses": misses, "size": size,
                "capacity": self.capacity,
                "hit_rate": (hits / total) if total else 0.0}

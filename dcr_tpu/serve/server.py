"""stdlib HTTP front end for the generation service.

Endpoints (JSON in/out):

- ``POST /generate`` — body ``{"prompt": str, "seed"?: int, "steps"?: int,
  "guidance"?: float, "sampler"?: str, "rand_noise_lam"?: float}``. Replies
  200 with ``{"id", "image_png_b64", "width", "height", "cache_hit",
  "latency_ms"}``; 400 on malformed input or invalid bucket parameters
  (validated BEFORE any compile); 503 with ``{"error":
  "overloaded"|"draining"|"bucket_limit"}`` on typed admission rejection;
  504 when the request exceeds the configured wait bound.
- ``POST /check`` — copy-risk query: body ``{"image_png_b64": <base64>}``
  scores one image against the configured train-embedding index (200 with
  ``{max_sim, top_key, flagged, topk, threshold}``; 503 + risk status while
  no index is loaded).
- ``GET /healthz`` — 200 ``{"status": "ok"|"draining", ..., "risk":
  "absent"|"loading"|"ok"|"failed"}`` (load balancers pull a draining
  replica out of rotation before its port closes; the risk field makes a
  worker serving unscored — failed index load — visible).
- ``GET /metrics`` — the :meth:`GenerationService.status` document: queue
  depth, batch occupancy, cache hit rate, p50/p99 latency.
  ``GET /metrics?format=prometheus`` renders the process-wide telemetry
  registry (core/tracing.py) — the same document plus ``faults/*`` counters
  and latency summaries — in Prometheus text exposition format for scrapes.
- ``GET /slo`` — the fleet supervisor's declarative SLO document
  (``obs/slo.py``): per-objective state (ok/warn/breach), burn rates,
  targets and breach counters; 404 on a service without an SLO engine
  (single-process worker).

``http.server`` is deliberate: zero new dependencies, and the threading
server's one-thread-per-connection model matches the workload — handler
threads only tokenize and block on a Future while the single worker thread
owns the device. ``block_on_close`` + non-daemon handler threads give the
drain guarantee: ``server_close()`` returns only after every in-flight
response has been written.
"""

from __future__ import annotations

import base64
import io
import json
import logging
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from dcr_tpu.core import tracing
from dcr_tpu.core.config import ServeConfig
from dcr_tpu.sampling import fastsample
from dcr_tpu.serve.queue import (AdmissionError, BucketLimitError,
                                 DrainingError, GenBucket,
                                 InvalidRequestError, MemoryBudgetError,
                                 NoWorkersError, QueueFullError, SloShedError)
from dcr_tpu.serve.worker import MAX_STEPS, GenerationService

log = logging.getLogger("dcr_tpu")

_ALLOWED_OVERRIDES = ("seed", "steps", "guidance", "sampler", "rand_noise_lam",
                      "resolution", "fast_ratio", "fast_order")

# typed admission rejection -> (HTTP status, wire error tag). SloShedError
# and NoWorkersError additionally carry a Retry-After hint so balancers and
# well-behaved clients back off for a concrete interval instead of retrying
# into the same overload.
_ADMISSION_RESPONSES = (
    (InvalidRequestError, 400, "bad_request"),
    (QueueFullError, 503, "overloaded"),
    (BucketLimitError, 503, "bucket_limit"),
    (MemoryBudgetError, 503, "memory_budget"),
    (DrainingError, 503, "draining"),
    (SloShedError, 503, "shed"),
    (NoWorkersError, 503, "no_workers"),
)


def admission_response(e: AdmissionError) -> tuple[int, dict, dict]:
    """(status, payload, extra headers) for a typed admission rejection."""
    for cls, code, tag in _ADMISSION_RESPONSES:
        if isinstance(e, cls):
            payload = ({"error": f"bad request: {e}"} if code == 400
                       else {"error": tag, "detail": str(e)})
            headers = {}
            retry_after = getattr(e, "retry_after_s", None)
            if retry_after is not None:
                headers["Retry-After"] = str(max(1, round(retry_after)))
            return code, payload, headers
    return 503, {"error": "overloaded", "detail": str(e)}, {}


def png_bytes(image: np.ndarray) -> bytes:
    """float32 [H, W, 3] in [0, 1] -> PNG (runs on handler threads, keeping
    the worker thread on device work only)."""
    from PIL import Image

    arr = (np.asarray(image) * 255.0).round().astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def request_bucket(service: GenerationService, body: dict) -> GenBucket:
    """Default bucket + per-request overrides. Unknown keys are a 400-class
    error (loud contract, not silent acceptance)."""
    unknown = set(body) - {"prompt"} - set(_ALLOWED_OVERRIDES)
    if unknown:
        raise ValueError(f"unknown request fields {sorted(unknown)!r}")
    d = service.default_bucket()
    steps = int(body.get("steps", d.steps))
    if not 1 <= steps <= MAX_STEPS:
        # bounds-checked BEFORE the canonical plan computation below, which
        # is O(steps) on the host — a hostile steps value must stay a typed
        # 400, never a giant allocation on the handler thread
        raise ValueError(f"steps must be in [1, {MAX_STEPS}], got {steps}")
    # every fast parameterization whose plan is dense maps onto ONE bucket
    # identity: a redundant override cannot burn an admission slot or
    # compile a twin of the dense program (invalid values pass through and
    # are rejected by validate_bucket at admission)
    fast_ratio, fast_order = fastsample.canonical_plan_params(
        steps, float(body.get("fast_ratio", d.fast_ratio)),
        int(body.get("fast_order", d.fast_order)))
    return GenBucket(
        resolution=int(body.get("resolution", d.resolution)),
        steps=steps,
        guidance=float(body.get("guidance", d.guidance)),
        sampler=str(body.get("sampler", d.sampler)),
        rand_noise_lam=float(body.get("rand_noise_lam", d.rand_noise_lam)),
        fast_ratio=fast_ratio,
        fast_order=fast_order,
    )


class ServeHandler(BaseHTTPRequestHandler):
    service: GenerationService      # set by make_server on the subclass
    cfg: ServeConfig
    protocol_version = "HTTP/1.1"
    # socket timeout for reads BETWEEN requests on a keep-alive connection
    # (and for slow request reads). Without it, an idle connection-pool
    # socket parks its handler thread in rfile.readline() forever, and the
    # drain's server_close() — which joins handler threads — never returns,
    # so the exit-83 contract would silently never fire.
    timeout = 15

    def log_message(self, fmt, *args):  # route access logs through logging
        log.debug("serve http: " + fmt, *args)

    def _reply(self, code: int, payload: dict,
               headers: Optional[dict] = None) -> None:
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _reply_text(self, code: int, text: str,
                    content_type: str = "text/plain; version=0.0.4") -> None:
        data = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:
        url = urlparse(self.path)
        if url.path == "/healthz":
            # never "ok" before the warm plan is compiled: services report a
            # readiness document ({status, buckets_warm, buckets_total} on a
            # worker; worker readiness counts on a fleet supervisor) so
            # balancers and supervisors can gate on actual compiled state
            doc_fn = getattr(self.service, "health_doc", None)
            if callable(doc_fn):
                self._reply(200, doc_fn())
                return
            health = getattr(self.service, "health", None)
            status = (health() if callable(health)
                      else "draining" if self.service.draining else "ok")
            self._reply(200, {"status": status})
        elif url.path == "/metrics":
            fmt = parse_qs(url.query).get("format", ["json"])[0]
            if fmt == "prometheus":
                merged = getattr(self.service, "prometheus_merged", None)
                if callable(merged):
                    # fleet supervisor: its own registry plus every worker's
                    # scraped registry, worker="N"-labeled — built from the
                    # bounded-timeout scrape cache, so a dead worker can
                    # never hang this handler
                    self._reply_text(200, merged())
                    return
                # fold the live service document into registry gauges, then
                # render the whole registry (incl. faults/* counters and the
                # request-latency summary) in Prometheus text format
                status_doc = dict(self.service.status())
                status_doc.pop("compiled_buckets", None)  # not numeric
                tracing.update_gauges(status_doc, prefix="serve/")
                self._reply_text(200, tracing.registry().prometheus_text())
            else:
                self._reply(200, self.service.status())
        elif url.path == "/slo":
            slo_fn = getattr(self.service, "slo_doc", None)
            if not callable(slo_fn):
                self._reply(404, {"error": "slo engine not supported"})
                return
            try:
                self._reply(200, slo_fn())
            except Exception as e:
                self._reply(500, {"error": f"slo status failed: {e!r}"})
        elif url.path == "/debug/profile":
            status_fn = getattr(self.service, "profile_status", None)
            if not callable(status_fn):
                self._reply(404, {"error": "profiling not supported"})
                return
            try:
                self._reply(200, status_fn())
            except Exception as e:
                self._reply(500, {"error": f"profile status failed: {e!r}"})
        else:
            self._reply(404, {"error": f"no such endpoint {self.path!r}"})

    def _parse_one(self, body: dict) -> tuple[str, int, GenBucket]:
        if not isinstance(body, dict):
            raise ValueError("body must be a JSON object")
        prompt = body["prompt"]
        if not isinstance(prompt, str) or not prompt.strip():
            raise ValueError("'prompt' must be a non-empty string")
        bucket = request_bucket(self.service, body)
        return prompt, int(body.get("seed", 0)), bucket

    def _render(self, req, result) -> dict:
        """The /generate response document. A fleet supervisor's future
        resolves to the worker's already-rendered document (dict) — passed
        through verbatim, bar the id, so a response is bit-identical whether
        the batch ran on worker 0, worker 3, or a respawn after a crash. A
        single-process service resolves to the raw image array."""
        if isinstance(result, dict):
            return {**result, "id": req.id, "latency_ms": None}
        return {
            "id": req.id,
            "image_png_b64": base64.b64encode(png_bytes(result)).decode(),
            "width": int(result.shape[1]),
            "height": int(result.shape[0]),
            "cache_hit": bool(req.cache_hit),
            # copy-risk verdict ({max_sim, top_key, flagged, topk}) when a
            # train-embedding index is loaded; null = unscored
            "copy_risk": req.risk,
            "latency_ms": None,  # client-side wall time is the honest number
        }

    def do_POST(self) -> None:
        if self.path == "/generate":
            self._post_generate()
        elif self.path == "/generate_batch":
            self._post_generate_batch()
        elif self.path == "/check":
            self._post_check()
        elif self.path == "/debug/profile":
            self._post_profile()
        else:
            self._reply(404, {"error": f"no such endpoint {self.path!r}"})

    def _post_check(self) -> None:
        """Copy-risk query (ROADMAP item 5's online endpoint): score one
        submitted image against the train-embedding index. Body
        ``{"image_png_b64": <base64 image>}``; replies 200 with ``{max_sim,
        top_key, flagged, topk, threshold, index_size}``, 503 + risk status
        while no loaded index can serve (absent/loading/failed — a worker
        that failed its index load is VISIBLE here, never a silent zero),
        400 on an undecodable body. On a fleet supervisor the query routes
        to the first ALIVE worker whose lease reports risk "ok"."""
        from dcr_tpu.obs.copyrisk import RiskUnavailableError

        check_fn = getattr(self.service, "check", None)
        if not callable(check_fn):
            self._reply(404, {"error": "copy-risk checking not supported"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e!r}"})
            return
        try:
            self._reply(200, check_fn(body))
        except RiskUnavailableError as e:
            self._reply(503, {"error": "risk_unavailable", "risk": e.status,
                              "detail": str(e)})
        except AdmissionError as e:
            self._reply(*admission_response(e))
        except (KeyError, TypeError, ValueError) as e:
            self._reply(400, {"error": f"bad request: {e!r}"})
        except Exception as e:
            self._reply(500, {"error": f"check failed: {e!r}"})

    def _post_profile(self) -> None:
        """Arm an on-demand jax.profiler capture: on a worker, around its own
        next K device steps; on a fleet supervisor, routed to a chosen (or
        the first alive) worker. Replies with the armed status including the
        artifact directory; poll GET /debug/profile until it reports the
        artifact written."""
        profile_fn = getattr(self.service, "profile", None)
        if not callable(profile_fn):
            self._reply(404, {"error": "profiling not supported"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e!r}"})
            return
        try:
            self._reply(200, profile_fn(body))
        except AdmissionError as e:
            self._reply(*admission_response(e))
        except (ValueError, RuntimeError) as e:
            # typed arming failures: already armed, unknown worker, no logdir
            self._reply(409, {"error": str(e)})
        except Exception as e:
            self._reply(500, {"error": f"profile arm failed: {e!r}"})

    def _post_generate(self) -> None:
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            prompt, seed, bucket = self._parse_one(body)
        except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e!r}"})
            return
        try:
            req = self.service.submit(prompt, seed=seed, bucket=bucket)
        except AdmissionError as e:
            self._reply(*admission_response(e))
            return
        try:
            result = req.future.result(timeout=self.cfg.request_timeout_s)
        except FutureTimeout:
            self._reply(504, {"error": "request timed out in queue/batch"})
            return
        except Exception as e:
            self._reply(500, {"error": f"generation failed: {e!r}"})
            return
        # respond leg of the request's span tree: PNG encode + socket write
        # happen on this handler thread, off the device worker's critical path
        with tracing.span("serve/respond", request_id=req.id,
                          parent=req.span.id if req.span is not None else None,
                          trace=req.trace_id):
            self._reply(200, self._render(req, result))

    def _post_generate_batch(self) -> None:
        """The fleet dispatch channel's wire call: a bucket-coherent batch
        submitted together, answered together. Item results are positional;
        a per-item failure is an ``{"error": ...}`` item (the supervisor
        fails exactly that request), while a malformed envelope is a 400
        (the supervisor requeues the whole batch elsewhere)."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"{}")
            items = body["requests"]
            if not isinstance(items, list) or not items:
                raise ValueError("'requests' must be a non-empty list")
        except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e!r}"})
            return
        reqs: list = []
        for item in items:
            try:
                # the dispatcher's distributed trace context rides next to
                # the generation fields; it is not a bucket override
                item = dict(item) if isinstance(item, dict) else item
                tctx = item.pop("trace", None) if isinstance(item, dict) else None
                prompt, seed, bucket = self._parse_one(item)
                reqs.append(self.service.submit(
                    prompt, seed=seed, bucket=bucket,
                    trace_ctx=tctx if isinstance(tctx, dict) else None))
            except (KeyError, TypeError, ValueError, AdmissionError) as e:
                reqs.append({"error": f"{type(e).__name__}: {e}"})
        results: list[dict] = []
        for req in reqs:
            if isinstance(req, dict):        # rejected at submit
                results.append(req)
                continue
            try:
                image = req.future.result(timeout=self.cfg.request_timeout_s)
            except Exception as e:  # timeout or generation failure: per-item
                results.append({"error": f"{type(e).__name__}: {e}"})
                continue
            with tracing.span("serve/respond", request_id=req.id,
                              parent=req.span.id if req.span is not None
                              else None, trace=req.trace_id):
                results.append(self._render(req, image))
        self._reply(200, {"results": results})


def make_server(cfg: ServeConfig,
                service: GenerationService) -> ThreadingHTTPServer:
    """ThreadingHTTPServer wired to the service. Handler threads are
    non-daemon and joined by ``server_close()`` (block_on_close), so the
    drain sequence can guarantee every accepted request gets its response."""
    handler = type("BoundServeHandler", (ServeHandler,),
                   {"service": service, "cfg": cfg})
    httpd = ThreadingHTTPServer((cfg.host, cfg.port), handler)
    httpd.daemon_threads = False
    httpd.block_on_close = True
    return httpd

"""Resident generation worker: compiled-sampler registry + batch execution.

The economics of online diffusion serving (DiffusionPipe, arXiv:2405.01248;
PFDiff, arXiv:2408.08822) are all amortization: compilation is paid once per
bucket, the text tower once per unique prompt, and the UNet scan — the real
work — runs over dynamically formed batches. This module is that resident
core, HTTP-free so benches and tests drive it in-process:

- one jitted sampler per :class:`~dcr_tpu.serve.queue.GenBucket`, compiled at
  a FIXED batch shape (``max_batch``, padded). One shape means one program
  AND bit-reproducible results: XLA fuses differently per batch size, so
  variable shapes would make an image depend on who it shared a batch with;
- per-request PRNG keys: every random draw for request i derives from
  ``fold_in(root, seed_i)`` and is generated per-row (vmap), so a prompt
  sampled alone is bit-identical to the same prompt inside a mixed batch;
- the prompt-embedding LRU (:mod:`dcr_tpu.serve.cache`) skips the CLIP text
  tower for repeated prompts;
- a wedged device step trips the coordination hang path (stack dump + exit
  89) via :func:`dcr_tpu.core.resilience.watchdog` instead of hanging the
  port until the scheduler notices.
"""

from __future__ import annotations

import contextlib
import logging
import os
import signal
import threading
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from dcr_tpu.core import resilience as R
from dcr_tpu.core import rng as rngmod
from dcr_tpu.core import tracing
from dcr_tpu.core import warmcache
from dcr_tpu.core.compile_surface import compile_surface
from dcr_tpu.core.config import ServeConfig
from dcr_tpu.core.metrics import LatencyTracker, MetricWriter
from dcr_tpu.models import schedulers as S
from dcr_tpu.models.vae import vae_scale_factor
from dcr_tpu.obs import memwatch
from dcr_tpu.sampling import fastsample
from dcr_tpu.sampling.pipeline import GenerationStack
from dcr_tpu.sampling.sampler import fast_plan_grid, scheduler_step
from dcr_tpu.serve.batcher import Batcher
from dcr_tpu.serve.cache import EmbeddingCache, embedding_key, mitigation_tag
from dcr_tpu.serve.queue import (AdmissionError, BucketLimitError,
                                 DrainingError, GenBucket,
                                 InvalidRequestError, MemoryBudgetError,
                                 Request, RequestQueue)
from dcr_tpu.utils import profiling

log = logging.getLogger("dcr_tpu")

SAMPLERS = ("ddim", "dpm++", "ddpm")
MAX_STEPS = 1000        # more denoising steps than train timesteps is nonsense
MAX_RESOLUTION = 4096


def validate_bucket(bucket: GenBucket, *, vae_scale: int) -> None:
    """Reject client-controlled bucket parameters BEFORE they reach jit:
    an invalid value must be a typed 400-class error, not a cryptic compile
    failure (500) — and never a compiled-and-cached degenerate program."""
    if bucket.sampler not in SAMPLERS:
        raise InvalidRequestError(
            f"sampler must be one of {SAMPLERS}, got {bucket.sampler!r}")
    if not 1 <= bucket.steps <= MAX_STEPS:
        raise InvalidRequestError(
            f"steps must be in [1, {MAX_STEPS}], got {bucket.steps}")
    if not (vae_scale <= bucket.resolution <= MAX_RESOLUTION
            and bucket.resolution % vae_scale == 0):
        raise InvalidRequestError(
            f"resolution must be a multiple of {vae_scale} in "
            f"[{vae_scale}, {MAX_RESOLUTION}], got {bucket.resolution}")
    if not 0.0 <= bucket.guidance <= 100.0:
        raise InvalidRequestError(
            f"guidance must be in [0, 100], got {bucket.guidance}")
    if not 0.0 <= bucket.rand_noise_lam <= 10.0:
        raise InvalidRequestError(
            f"rand_noise_lam must be in [0, 10], got {bucket.rand_noise_lam}")
    if not 0.0 <= bucket.fast_ratio <= fastsample.MAX_REUSE_RATIO:
        raise InvalidRequestError(
            f"fast_ratio must be in [0, {fastsample.MAX_REUSE_RATIO}], "
            f"got {bucket.fast_ratio}")
    if bucket.fast_order not in (1, 2):
        raise InvalidRequestError(
            f"fast_order must be 1 or 2, got {bucket.fast_order}")


@compile_surface("serve/batch_sampler")
def make_batch_sampler(bucket: GenBucket, models, root_seed: int,
                       batch_size: int):
    """Jitted ``(params, cond, uncond, seeds) -> images`` for one bucket.

    cond/uncond: [B, L, D] prompt embeddings (already encoded/cached);
    seeds: [B] uint32 per-request seeds. Every stochastic draw for row i uses
    only ``fold_in(root_key(root_seed), seeds[i])``-derived keys, generated
    per-row, so row i's image is a pure function of (params, cond[i],
    seeds[i]) — batch composition cannot perturb it.
    """
    sched = models.schedule
    ts, prev_ts, lower_order_final, plan = fast_plan_grid(
        bucket.sampler, sched, bucket.steps, bucket.fast_ratio)
    # dense plan => the ORIGINAL scan body, bit-identical to the pre-fast
    # sampler; a reuse plan is a distinct compiled program for this bucket
    use_fast = not fastsample.is_dense(plan)
    latent_size = bucket.resolution // vae_scale_factor(models.vae.config)
    latent_ch = models.vae.config.vae_latent_channels
    scaling = models.vae.config.vae_scaling_factor
    guidance = bucket.guidance
    lam = bucket.rand_noise_lam

    def sample_fn(params, cond, uncond, seeds):
        if cond.shape[0] != batch_size:  # dcr-lint: disable=DCR007 — branch on a STATIC shape, not a traced value: this is the trace-time guard that RAISES before a second batch shape can compile (the exact recompile hazard DCR007 polices)
            # trace-time guard for the load-bearing fixed-shape invariant:
            # a caller skipping execute()'s padding would otherwise silently
            # compile a second program and break batch-composition
            # bit-reproducibility (XLA fuses differently per shape)
            raise ValueError(
                f"batch sampler for {bucket} is compiled at batch="
                f"{batch_size}; got {cond.shape[0]} rows — pad the batch")
        root = rngmod.root_key(root_seed)
        keys = jax.vmap(lambda s: jax.random.fold_in(root, s))(seeds)
        if lam > 0.0:
            # Newpipe mitigation noise, per-request: fresh noise even for a
            # cache-hit embedding, independent of the rest of the batch
            def noise_pair(c, u, k):
                k1, k2 = jax.random.split(rngmod.stream_key(k, "emb_noise"))
                return (c + lam * jax.random.normal(k1, c.shape, c.dtype),
                        u + lam * jax.random.normal(k2, u.shape, u.dtype))
            cond, uncond = jax.vmap(noise_pair)(cond, uncond, keys)
        ctx = jnp.concatenate([uncond, cond], axis=0)      # [2B, L, D]

        x = jax.vmap(lambda k: jax.random.normal(
            rngmod.stream_key(k, "init"),
            (latent_size, latent_size, latent_ch)))(keys)  # [B, h, w, c]
        step_keys = jax.vmap(lambda k: rngmod.stream_key(k, "steps"))(keys)

        def denoise(carry, step_idx):
            if use_fast:
                x, dpm_state, bank = carry
            else:
                x, dpm_state = carry
            t = ts[step_idx]
            prev_t = prev_ts[step_idx]
            bsz = x.shape[0]

            def predict():
                tb = jnp.full((2 * bsz,), t, jnp.int32)
                pred = models.unet.apply({"params": params["unet"]},
                                         jnp.concatenate([x, x], axis=0), tb,
                                         ctx)
                pred_uncond, pred_cond = jnp.split(pred, 2, axis=0)
                return pred_uncond + guidance * (pred_cond - pred_uncond)

            if use_fast:
                # elementwise over the batch, plan uniform per bucket: row
                # i's reuse/extrapolation depends only on row i's banked
                # scores, so batch-composition bit-independence survives
                pred, bank = fastsample.predict_or_reuse(
                    plan, step_idx, t, bank, bucket.fast_order, predict)
            else:
                pred = predict()
            if bucket.sampler == "ddpm":
                # per-row keys via vmap: the ancestral noise of request i
                # must not depend on batch position or neighbors (the bulk
                # pipeline draws ONE batch-shaped noise per step instead)
                x_new = jax.vmap(
                    lambda p_row, x_row, k_row: scheduler_step(
                        bucket.sampler, sched, p_row, x_row, t, prev_t, None,
                        noise_key=jax.random.fold_in(k_row, step_idx))[0])(
                    pred, x, step_keys)
                dpm_new = dpm_state
            else:
                force1 = jnp.logical_and(lower_order_final,
                                         step_idx == len(ts) - 1)
                x_new, dpm_new = scheduler_step(
                    bucket.sampler, sched, pred, x, t, prev_t, dpm_state,
                    force_first_order=force1)
            if use_fast:
                return (x_new, dpm_new, bank), ()
            return (x_new, dpm_new), ()

        init = (x, S.dpm_init_state(x.shape))
        if use_fast:
            init = init + (fastsample.bank_init(x.shape),)
        (x, *_), _ = jax.lax.scan(denoise, init, jnp.arange(len(ts)))
        images = models.vae.apply({"params": params["vae"]}, x / scaling,
                                  method=models.vae.decode)
        return jnp.clip(images * 0.5 + 0.5, 0.0, 1.0)

    return jax.jit(sample_fn)


@compile_surface("serve/encode")
def make_text_encoder(models):
    """Jitted ``(text_params, ids) -> [B, L, D]`` prompt-embedding step — the
    text tower every cache miss pays. One compiled program per ids shape;
    the service always tokenizes to the model's fixed max length, so in
    practice it compiles once per process."""
    return jax.jit(
        lambda text_params, ids: models.text_encoder.apply(
            {"params": text_params}, ids).last_hidden_state)


class ServeMetrics:
    """Counters + latency reservoir behind one lock; snapshots feed both the
    /metrics endpoint and the MetricWriter scalars."""

    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.rejected_overload = 0
        self.rejected_draining = 0
        self.rejected_invalid = 0
        self.rejected_bucket_limit = 0
        self.rejected_memory_budget = 0
        self.completed_total = 0
        self.failed_total = 0
        self.batches_total = 0
        self.occupancy_last = 0.0
        self.occupancy_max = 0.0
        self._occupancy_sum = 0.0
        # named: registers in the process-wide telemetry registry, so request
        # latency percentiles ride Prometheus scrapes and flight-rec dumps
        self.latency = LatencyTracker(name="serve/request_latency_s")

    def note_submitted(self) -> None:
        with self._lock:
            self.requests_total += 1

    def note_rejected(self, error: AdmissionError) -> None:
        with self._lock:
            if isinstance(error, DrainingError):
                self.rejected_draining += 1
            elif isinstance(error, InvalidRequestError):
                self.rejected_invalid += 1
            elif isinstance(error, BucketLimitError):
                self.rejected_bucket_limit += 1
            elif isinstance(error, MemoryBudgetError):
                self.rejected_memory_budget += 1
            else:
                self.rejected_overload += 1

    def note_batch(self, n_real: int, batch_size: int, ok: bool) -> None:
        occ = n_real / max(1, batch_size)
        with self._lock:
            self.batches_total += 1
            self.occupancy_last = occ
            self.occupancy_max = max(self.occupancy_max, occ)
            self._occupancy_sum += occ
            if ok:
                self.completed_total += n_real
            else:
                self.failed_total += n_real

    def snapshot(self) -> dict:
        with self._lock:
            batches = self.batches_total
            d = {
                "requests_total": self.requests_total,
                "rejected_overload": self.rejected_overload,
                "rejected_draining": self.rejected_draining,
                "rejected_invalid": self.rejected_invalid,
                "rejected_bucket_limit": self.rejected_bucket_limit,
                "rejected_memory_budget": self.rejected_memory_budget,
                "completed_total": self.completed_total,
                "failed_total": self.failed_total,
                "batches_total": batches,
                "batch_occupancy_last": self.occupancy_last,
                "batch_occupancy_max": self.occupancy_max,
                "batch_occupancy_avg": (self._occupancy_sum / batches
                                        if batches else 0.0),
            }
        pct = self.latency.percentiles((50, 99))
        d["latency_ms"] = {k: round(v * 1000.0, 3) for k, v in pct.items()}
        return d


class GenerationService:
    """The resident serving core: queue + batcher + cache + compiled samplers.

    HTTP-free by design — :mod:`dcr_tpu.serve.server` fronts it for network
    traffic, while benches and tests call :meth:`submit`/:meth:`execute`
    directly. One worker thread drains the queue; handler threads only
    tokenize-and-wait.
    """

    def __init__(self, cfg: ServeConfig, stack: GenerationStack, *,
                 writer: Optional[MetricWriter] = None):
        self.cfg = cfg
        self.stack = stack
        self.queue = RequestQueue(cfg.queue_depth)
        self.batcher = Batcher(cfg.max_batch, cfg.max_wait_ms / 1000.0)
        self.cache = EmbeddingCache(cfg.cache_entries)
        self.metrics = ServeMetrics()
        self._writer = writer
        self._samplers: dict[GenBucket, object] = {}
        # buckets counted against max_compiled_buckets at ADMISSION time, not
        # first compile — otherwise a burst of novel buckets all passes the
        # budget check before the worker compiles any of them
        self._admitted_buckets: set[GenBucket] = set()
        self._samplers_lock = threading.Lock()
        self._vae_scale = vae_scale_factor(stack.models.vae.config)
        # a misconfigured default bucket must fail at STARTUP, not boot a
        # healthy-looking replica that 400s every default request
        validate_bucket(self.default_bucket(), vae_scale=self._vae_scale)
        # dcr-hbm: live dcr_device_mem_* gauges for /metrics and the fleet
        # scrape (graceful no-op where the backend reports no stats)
        memwatch.start_sampler()
        # persistent executable cache (dcr-warm): compiled samplers/encoder
        # are loaded from disk when a verified entry exists, so a respawn
        # reaches ready without paying XLA again
        self._warmcache = (warmcache.WarmCache(cfg.warm.dir)
                           if cfg.warm.dir else None)
        # serializes AOT compiles; kept separate from _samplers_lock so a
        # multi-second compile never blocks admission threads checking the
        # bucket budget
        self._build_lock = threading.Lock()
        # warm-start readiness: begin_warm() computes the plan and flips
        # health to "warming"; warm_start() compiles it and flips back. The
        # event starts SET so in-process services that never warm (tests,
        # benches) report "ok" exactly as before dcr-warm.
        self._warm_plan: Optional[list[GenBucket]] = None
        self._warm_complete = threading.Event()
        self._warm_complete.set()
        self._encode_jit = make_text_encoder(stack.models)
        self._encode = self._encode_jit
        self._tok_fp = stack.tokenizer.fingerprint()
        # copy-risk scoring (dcr-watch): the train-embedding index loads in
        # the BACKGROUND — a multi-GB index (or its SSCD compile) must never
        # delay the port or admission. Until it terminalizes, batches go
        # unscored (copy_risk: null); a failed load degrades to
        # scoring-disabled with a counter, never a dead worker.
        self._risk = None
        self._risk_status = "absent"
        self._risk_done = threading.Event()
        self._pump = None             # IngestPump (dcr-live), risk+ingest on
        self._evidence = None
        self._risk_thread: Optional[threading.Thread] = None
        if cfg.risk.index_path or cfg.risk.store_dir:
            self._risk_status = "loading"
            self._risk_thread = threading.Thread(
                target=self._load_risk_index, daemon=True,
                name="risk-index-load")
            self._risk_thread.start()
        else:
            self._risk_done.set()
        self._uncond: Optional[np.ndarray] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-process batch index: the `batch` coordinate of the serve-side
        # fault kinds (worker_crash / worker_hang / slow_step)
        self._batch_index = 0

    # -- request plumbing ----------------------------------------------------

    def default_bucket(self) -> GenBucket:
        c = self.cfg
        ratio, order = fastsample.canonical_plan_params(
            c.num_inference_steps,
            c.fast.reuse_ratio if c.fast.enabled else 0.0, c.fast.order)
        return GenBucket(resolution=c.resolution, steps=c.num_inference_steps,
                         guidance=c.guidance_scale, sampler=c.sampler,
                         rand_noise_lam=c.rand_noise_lam,
                         fast_ratio=ratio, fast_order=order)

    def submit(self, prompt: str, *, seed: int = 0,
               bucket: Optional[GenBucket] = None,
               trace_ctx: Optional[dict] = None) -> Request:
        """Admit a request. Typed AdmissionError on every rejection path:
        InvalidRequestError (bad bucket params), BucketLimitError (would
        compile past the resident-program budget), QueueFullError (overload),
        DrainingError (SIGTERM seen).

        ``trace_ctx`` is the distributed trace context a fleet supervisor
        ships with a dispatched batch (:func:`dcr_tpu.core.tracing.
        wire_context`): when present, this worker's ``serve/request`` span
        joins the supervisor's trace — same trace id, ``remote_parent``
        naming the supervisor root span, ``attempt`` tagging requeued
        re-executions as siblings — instead of starting a disconnected tree.
        """
        bucket = bucket or self.default_bucket()
        try:
            validate_bucket(bucket, vae_scale=self._vae_scale)
            with self._samplers_lock:
                bucket_added = bucket not in self._admitted_buckets
                if bucket_added:
                    if (len(self._admitted_buckets)
                            >= self.cfg.max_compiled_buckets):
                        raise BucketLimitError(
                            f"bucket {bucket} would exceed the resident "
                            f"compiled-sampler budget "
                            f"({self.cfg.max_compiled_buckets}); use an "
                            "already-served parameter combination")
                    # dcr-hbm containment: a NOVEL bucket is a new resident
                    # compiled program — consult the live-surface footprints
                    # before admitting it, so one adversarial request can't
                    # OOM a warm worker (typed 503, never a dead port)
                    self._check_memory_budget(bucket)
                    self._admitted_buckets.add(bucket)
            req = Request(prompt=prompt, seed=int(seed) & 0xFFFFFFFF,
                          bucket=bucket)
            trace_attrs: dict = {}
            if trace_ctx and trace_ctx.get("trace_id"):
                req.trace_id = str(trace_ctx["trace_id"])
                if trace_ctx.get("parent_span") is not None:
                    trace_attrs["remote_parent"] = int(trace_ctx["parent_span"])
                if trace_ctx.get("attempt") is not None:
                    trace_attrs["attempt"] = int(trace_ctx["attempt"])
            else:
                req.trace_id = tracing.new_trace_id()
            # root of this request's span tree (admission -> queue wait ->
            # device step -> respond), closed by the future callback whichever
            # thread resolves it — so the root span's duration IS the
            # request's in-service latency. Attached BEFORE queue.submit
            # publishes the request: the worker can flush a full bucket and
            # read req.span before this thread runs another line. A rejected
            # request's handle is simply never ended (nothing is recorded).
            root = tracing.begin_span("serve/request", parent=None,
                                      trace=req.trace_id,
                                      request_id=req.id, seed=req.seed,
                                      bucket=str(tuple(bucket)), **trace_attrs)
            req.span = root
            try:
                self.queue.submit(req)
            except AdmissionError:
                # a never-queued novel bucket must not consume a resident-
                # program slot (and, under dcr-hbm, a phantom byte
                # reservation) forever. Kept when a concurrently-queued
                # request or a resident sampler still carries it — the rare
                # concurrent-admit race then at worst over-counts by the
                # one slot left registered (the supervisor makes the same
                # trade).
                if bucket_added:
                    with self._samplers_lock:
                        if (bucket not in self._samplers
                                and not self.queue.has_bucket(bucket)):
                            self._admitted_buckets.discard(bucket)
                raise
        except AdmissionError as e:
            self.metrics.note_rejected(e)
            tracing.event("serve/rejected", error=type(e).__name__)
            raise
        self.metrics.note_submitted()
        # safe after submit: add_done_callback fires immediately on an
        # already-resolved future, and .end() is idempotent
        req.future.add_done_callback(
            lambda f: root.end(error=repr(f.exception()))
            if f.exception() is not None else root.end())
        return req

    def _check_memory_budget(self, bucket: GenBucket) -> None:
        """Reject a novel bucket whose estimated footprint exceeds remaining
        device memory (caller holds ``_samplers_lock``). The estimate is the
        largest non-argument footprint among this process's live
        ``serve/batch_sampler`` programs (same model, same padded batch
        shape — only baked-in statics differ); no live sibling or no
        backend stats means no check, exactly the pre-dcr-hbm behavior.

        Admitted-but-not-yet-compiled novel buckets RESERVE the estimate:
        live stats only move once a program actually compiles, so without
        the reservation a burst of distinct novel buckets would all pass
        against the same unchanged reading and OOM together — the exact
        hole this check exists to close."""
        estimate = memwatch.estimate_surface_bytes("serve/batch_sampler")
        if estimate is None:
            return
        remaining = memwatch.remaining_device_bytes()
        if remaining is None:
            return
        pending = sum(1 for b in self._admitted_buckets
                      if b not in self._samplers)
        needed = estimate * (pending + 1)
        if needed > remaining:
            tracing.registry().counter(
                "serve/rejected_memory_budget").inc()
            R.log_event("memory_budget_rejected", bucket=str(tuple(bucket)),
                        estimate_bytes=estimate, pending_compiles=pending,
                        needed_bytes=needed, remaining_bytes=remaining)
            raise MemoryBudgetError(
                f"bucket {bucket} would compile a new resident program "
                f"(~{estimate} bytes estimated from live surfaces; "
                f"{pending} admitted compile(s) already pending) past "
                f"remaining device memory ({remaining} bytes); use an "
                "already-served parameter combination")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-worker")
        self._thread.start()

    def begin_drain(self) -> None:
        """Stop admission; the worker keeps going until the queue is empty."""
        self.queue.close()
        self._stop.set()

    def join_drained(self, timeout: Optional[float] = None) -> bool:
        """Wait for the worker to finish the backlog; True when fully drained."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive() and self.queue.empty()

    def stop(self, timeout: Optional[float] = None) -> bool:
        self.begin_drain()
        drained = self.join_drained(timeout)
        pump = self._pump
        if pump is not None:
            # after the worker drained: the pump finishes its queued
            # backlog (WAL-durable) and releases the writer lease
            pump.stop()
        return drained

    @property
    def draining(self) -> bool:
        return self.queue.closed

    # -- execution -----------------------------------------------------------

    def _sampler_for(self, bucket: GenBucket):
        with self._samplers_lock:
            fn = self._samplers.get(bucket)
        if fn is not None:
            return fn
        with self._build_lock:
            # double-checked: the worker thread and warm_start can race on
            # the same bucket; the second builder reuses the first's program
            with self._samplers_lock:
                fn = self._samplers.get(bucket)
                if fn is not None:
                    return fn
            fn = self._build_sampler(bucket)
            with self._samplers_lock:
                self._samplers[bucket] = fn
        return fn

    def _build_sampler(self, bucket: GenBucket):
        """AOT-lower the bucket's sampler and resolve it through the warm
        cache: a verified cache entry deserializes in O(load); otherwise XLA
        compiles now and the executable is persisted for the next
        incarnation. Returns a ready-to-call program (with a one-way degrade
        to the plain jit path should the executable ever reject its inputs)."""
        L = self.stack.model_cfg.text_max_length
        D = self.stack.model_cfg.text_hidden_size
        jit_fn = make_batch_sampler(bucket, self.stack.models,
                                    self.cfg.seed, self.cfg.max_batch)
        emb = jax.ShapeDtypeStruct((self.cfg.max_batch, L, D), jnp.float32)
        seeds = jax.ShapeDtypeStruct((self.cfg.max_batch,), jnp.uint32)
        res = warmcache.aot_compile(
            "serve/batch_sampler", jit_fn,
            (self.stack.params, emb, emb, seeds),
            static_config={
                "resolution": bucket.resolution, "steps": bucket.steps,
                "guidance": bucket.guidance, "sampler": bucket.sampler,
                "rand_noise_lam": bucket.rand_noise_lam,
                "max_batch": self.cfg.max_batch,
                # the fast plan is derived from these: a different plan is a
                # different program, so it must be a different cache key
                "fast_ratio": bucket.fast_ratio,
                "fast_order": bucket.fast_order,
            },
            cache=self._warmcache)
        if res.source == "cache":
            log.info("serve: bucket %s warm-loaded from cache in %.2fs "
                     "(batch=%d)", bucket, res.build_s, self.cfg.max_batch)
        else:
            # trace_report counts these per bucket AND per process
            # incarnation (os_pid): a warm respawn must show zero
            log.info("serve: compiled sampler for bucket %s at batch=%d "
                     "in %.2fs", bucket, self.cfg.max_batch, res.build_s)
            tracing.event("serve/compile", bucket=str(tuple(bucket)),
                          max_batch=self.cfg.max_batch, os_pid=os.getpid())
        if self._warmcache is not None and self._warm_complete.is_set():
            # record a lazily admitted bucket for the NEXT incarnation's
            # warm plan. LRU + budget-capped: active buckets move to the
            # manifest tail, stale ones age out the front — a long-lived
            # shared cache dir can never fill every future incarnation's
            # resident-program budget with history. During the warm phase
            # itself this is skipped: warm_start() records the whole plan in
            # ONE batched update instead of a read-merge-rewrite per bucket.
            warmcache.update_warm_manifest(
                self.cfg.warm.dir, [list(tuple(bucket))],
                max_entries=self.cfg.max_compiled_buckets)
        return warmcache.guarded(res.fn, jit_fn, "serve/batch_sampler")

    # -- warm-start readiness (dcr-warm) -------------------------------------

    def begin_warm(self) -> int:
        """Enter the warming state and compute the warm plan: the default
        bucket plus valid buckets from the previous incarnation's warm
        manifest NEWEST-first (the manifest is LRU-ordered), capped by the
        compiled-bucket budget. /healthz reports "warming" from here until
        :meth:`warm_start` finishes. Returns the plan size (0 = warm start
        disabled)."""
        if not self.cfg.warm.warm_start:
            return 0
        plan = [self.default_bucket()]
        if self._warmcache is not None:
            from dcr_tpu.serve.fleet import bucket_from_tuple

            for entry in reversed(
                    warmcache.read_warm_manifest(self.cfg.warm.dir)):
                try:
                    b = bucket_from_tuple(entry)
                    validate_bucket(b, vae_scale=self._vae_scale)
                except (TypeError, ValueError, InvalidRequestError) as e:
                    # a stale hint (config change, hand edit) costs a log
                    # line, never a boot
                    R.log_event("warm_manifest_entry_invalid", entry=entry,
                                error=repr(e))
                    R.bump_counter("warmcache/manifest_entry_invalid")
                    continue
                if b not in plan:
                    plan.append(b)
        # the plan must leave ADMISSION HEADROOM: warm buckets enter
        # _admitted_buckets (they are resident programs), and compiled
        # programs never evict — a plan that filled the whole budget with
        # the previous incarnation's traffic would 503 every novel bucket
        # for this process's lifetime AND keep the manifest from ever
        # learning the new traffic (rejected buckets never compile). One
        # reserved slot breaks that wedge: the novel bucket admits,
        # compiles, and the LRU manifest warms it next incarnation.
        cap = max(1, self.cfg.max_compiled_buckets - 1)
        if len(plan) > cap:
            R.log_event("warm_plan_over_budget", planned=len(plan), cap=cap,
                        budget=self.cfg.max_compiled_buckets)
            plan = plan[:cap]
        self._warm_plan = plan
        self._warm_complete.clear()
        return len(plan)

    def warm_start(self) -> dict:
        """Execute the warm plan: text encoder + uncond embedding first
        (every batch needs them), then one resident program per planned
        bucket — each from the persistent cache when a verified entry
        exists. Flips /healthz from "warming" to "ok" when done."""
        if not self.cfg.warm.warm_start:
            return {"buckets_warm": 0, "buckets_total": 0, "seconds": 0.0}
        if self._warm_plan is None:
            self.begin_warm()
        t0 = time.monotonic()
        self._warm_encoder()
        self._uncond_embedding()
        for bucket in self._warm_plan:
            with self._samplers_lock:
                self._admitted_buckets.add(bucket)
            self._sampler_for(bucket)
        if self._warmcache is not None:
            # one batched manifest update for the whole plan (per-bucket
            # updates during warming are suppressed in _build_sampler)
            warmcache.update_warm_manifest(
                self.cfg.warm.dir,
                [list(tuple(b)) for b in self._warm_plan],
                max_entries=self.cfg.max_compiled_buckets)
        self._warm_complete.set()
        doc = {"buckets_warm": len(self._warm_plan),
               "buckets_total": len(self._warm_plan),
               "seconds": round(time.monotonic() - t0, 3)}
        R.log_trace("warm_start_done", **doc)
        return doc

    def _warm_encoder(self) -> None:
        """AOT the text-encoder program through the warm cache (the tower
        every cache-miss embedding pays)."""
        ids = self.stack.tokenizer([""])
        res = warmcache.aot_compile(
            "serve/encode", self._encode_jit,
            (self.stack.params["text"], ids),
            static_config={
                "text_max_length": self.stack.model_cfg.text_max_length},
            cache=self._warmcache)
        self._encode = warmcache.guarded(res.fn, self._encode_jit,
                                         "serve/encode")

    def health(self) -> str:
        if self.draining:
            return "draining"
        if not self._warm_complete.is_set():
            return "warming"
        return "ok"

    def health_doc(self) -> dict:
        """The /healthz document: never plain "ok" before the warm plan is
        compiled — balancers and the fleet supervisor gate on it."""
        with self._samplers_lock:
            warm = len(self._samplers)
        total = max(len(self._warm_plan or ()), warm)
        doc = {"status": self.health(), "buckets_warm": warm,
               "buckets_total": total, "risk": self._risk_status}
        if self._pump is not None:
            doc["ingest"] = self._pump.stats()
        return doc

    def _uncond_embedding(self) -> np.ndarray:
        if self._uncond is None:
            ids = self.stack.tokenizer([""])
            self._uncond = np.asarray(
                self._encode(self.stack.params["text"], ids))[0]
        return self._uncond

    def _cond_embedding(self, req: Request, mitigation: str) -> np.ndarray:
        key = embedding_key(self._tok_fp, req.prompt, mitigation)
        emb = self.cache.get(key)
        req.cache_hit = emb is not None
        if emb is None:
            ids = self.stack.tokenizer([req.prompt])
            emb = np.asarray(self._encode(self.stack.params["text"], ids))[0]
            self.cache.put(key, emb)
        return emb

    # -- copy-risk scoring (dcr-watch) ---------------------------------------

    def _load_risk_index(self) -> None:
        """Background loader: dump -> verified index -> compiled pipeline
        (extractor + top-k scorer through warmcache). Flips risk status
        loading -> ok|failed; /healthz and the fleet lease report it."""
        from dcr_tpu.obs.copyrisk import CopyRiskIndex, EvidenceRecorder

        cfg = self.cfg
        source = cfg.risk.store_dir or cfg.risk.index_path
        try:
            with R.stage("risk_index_load"):
                index = CopyRiskIndex.load(cfg.risk, batch=cfg.max_batch,
                                           warm_dir=cfg.warm.dir)
        except Exception as e:
            R.log_event("risk_index_load_failed", path=source,
                        error=repr(e))
            R.bump_counter("copy_risk/index_load_failed")
            self._risk_status = "failed"
            self._risk_done.set()
            return
        ev_dir = cfg.risk.evidence_dir
        if not ev_dir:
            base = tracing.trace_dir()
            ev_dir = str(base / "risk_evidence") if base is not None else ""
        self._evidence = EvidenceRecorder(ev_dir or None,
                                          cfg.risk.max_evidence)
        if cfg.risk.ann and cfg.slo.enabled:
            # dcr-slo: sampled shadow-exact recall probe rides the ANN
            # scoring path — the full-probe query is its own exact oracle
            from dcr_tpu.obs.recall_probe import RecallProbe

            index.recall_probe = RecallProbe(
                every_n=cfg.slo.recall_probe_every_n,
                k=cfg.slo.recall_probe_k,
                window=cfg.slo.recall_probe_window)
        self._risk = index
        self._risk_status = "ok"
        self._risk_done.set()
        log.info("serve: copy-risk index ok — %d train embeddings from %s "
                 "(threshold %.3f%s)", len(index), source,
                 cfg.risk.threshold,
                 f", evidence -> {ev_dir}" if ev_dir else "")
        if cfg.ingest.enabled and cfg.risk.store_dir:
            self._start_ingest(index)

    def _start_ingest(self, index) -> None:
        """dcr-live: stream every scored generation's SSCD embedding into
        the store. The pump owns the writer lease and the compaction loop;
        the index's live-tail hook makes acked-but-uncompacted rows visible
        to `/check` and per-response scoring immediately."""
        from dcr_tpu.serve.ingest import IngestPump

        icfg = self.cfg.ingest
        pump = IngestPump(
            self.cfg.risk.store_dir, embed_dim=index._store.embed_dim,
            queue_max=icfg.queue_max, batch_rows=icfg.batch_rows,
            seal_rows=icfg.seal_rows, compact_rows=icfg.compact_rows,
            lease_s=icfg.lease_s,
            owner=f"serve-worker.{os.getpid()}",
            on_snapshot=lambda v: index.refresh_store())
        index.live_tail = pump.tail
        self._pump = pump.start()
        log.info("serve: live ingest on — store %s (queue %d, compact "
                 "every %d rows)", self.cfg.risk.store_dir, icfg.queue_max,
                 icfg.compact_rows)

    def risk_status(self) -> str:
        """absent | loading | ok | failed."""
        return self._risk_status

    def wait_risk_ready(self, timeout: float) -> bool:
        """True once the index load terminalized (ok OR failed)."""
        return self._risk_done.wait(timeout)

    def _score_risk(self, requests: list[Request], images: np.ndarray,
                    ids: list, traces: list) -> None:
        """Score one finished batch against the train index: `copy_risk` on
        each request, sim histogram + flagged counters, a `risk/flagged`
        event and bounded evidence dump per over-threshold generation. Any
        failure is counted and the batch ships unscored — scoring must
        never fail generation."""
        from dcr_tpu.obs import copyrisk

        index = self._risk
        if index is None:
            return
        rcfg = self.cfg.risk
        try:
            with tracing.span("serve/risk_score", batch=len(requests),
                              request_ids=ids, trace_ids=traces) as sp:
                scores, feats = index.score_batch_with_features(images)
                agg = copyrisk.observe_scores(scores, rcfg.threshold)
                # per-row sims/prompts ride the span: tools/risk_report's
                # per-prompt breakdown and trace_report's percentiles come
                # from here
                sp.attrs.update(
                    sims=[round(s.max_sim, 6) for s in scores],
                    prompts=[r.prompt for r in requests],
                    flagged=agg["flagged"])
        except Exception as e:
            R.log_event("risk_score_failed", batch=len(requests),
                        error=repr(e))
            R.bump_counter("copy_risk/score_failed")
            return
        for req, score, img in zip(requests, scores, images):
            req.risk = score.doc(rcfg.threshold)
            if score.max_sim >= rcfg.threshold:
                tracing.event("risk/flagged", trace=req.trace_id,
                              request_id=req.id, seed=req.seed,
                              prompt=req.prompt,
                              max_sim=round(score.max_sim, 6),
                              top_key=score.top_key,
                              threshold=rcfg.threshold)
                if self._evidence is not None:
                    self._evidence.record(
                        img, score, rcfg.threshold, request_id=req.id,
                        prompt=req.prompt, seed=req.seed,
                        bucket=list(tuple(req.bucket)), trace=req.trace_id)
        pump = self._pump
        if pump is not None:
            # enqueue-and-forget: offer() never blocks — a full queue drops
            # the row and bumps dcr_ingest_dropped_total, generation latency
            # is untouched (the bench_ingest p99 gate)
            for req, row in zip(requests, feats):
                pump.offer(row, f"gen/{req.trace_id or req.id}")

    def check(self, body: dict) -> dict:
        """``POST /check``: score ONE submitted image against the train
        index — ROADMAP item 5's online "is this a copy?" query. Body:
        ``{"image_png_b64": <base64 image>}``. Raises RiskUnavailableError
        (503) while the index is absent/loading/failed, ValueError (400) on
        an undecodable body."""
        from dcr_tpu.obs.copyrisk import (RiskUnavailableError,
                                          decode_image_b64)

        index = self._risk
        if index is None:
            raise RiskUnavailableError(
                f"risk index is {self._risk_status} (source="
                f"{(self.cfg.risk.store_dir or self.cfg.risk.index_path)!r})",
                status=self._risk_status)
        image = decode_image_b64(body)
        with tracing.span("serve/risk_score", source="check", batch=1) as sp:
            score = index.score_batch(image[None])[0]
            sp.attrs.update(sims=[round(score.max_sim, 6)])
        reg = tracing.registry()
        reg.counter("copy_risk/checked_total").inc()
        reg.histogram("copy_risk/sim").observe(score.max_sim)
        return {**score.doc(self.cfg.risk.threshold),
                "threshold": self.cfg.risk.threshold,
                "index_size": len(index)}

    def execute(self, requests: list[Request]) -> np.ndarray:
        """Run one bucket-coherent batch; returns float32 [n, H, W, 3].

        Pads to the fixed ``max_batch`` shape with uncond-embedding rows
        (results discarded), so every batch of a bucket hits the same
        compiled program regardless of occupancy.
        """
        if not requests:
            return np.zeros((0,), np.float32)
        bucket = requests[0].bucket
        assert all(r.bucket == bucket for r in requests), \
            "execute() requires a bucket-coherent batch"
        n = len(requests)
        pad = self.cfg.max_batch - n
        if pad < 0:
            raise ValueError(f"batch of {n} exceeds max_batch={self.cfg.max_batch}")
        fn = self._sampler_for(bucket)
        ids = [r.id for r in requests]
        traces = [r.trace_id for r in requests]
        # batch assembly: tokenize + text tower (or cache hit) + padding.
        # Batch-level spans carry the member request ids AND trace ids (the
        # fleet merge attributes batch time to each member's tree through
        # them); the per-request children (queue wait, respond) parent on
        # each request's root span.
        with tracing.span("serve/assemble", batch=n, request_ids=ids,
                          trace_ids=traces):
            mitigation = mitigation_tag(bucket)
            uncond_row = self._uncond_embedding()
            cond = np.stack([self._cond_embedding(r, mitigation) for r in requests]
                            + [uncond_row] * pad)
            uncond = np.stack([uncond_row] * self.cfg.max_batch)
            seeds = np.asarray([r.seed for r in requests] + [0] * pad, np.uint32)
        # profiling.capture is a no-op unless /debug/profile (or the trainer's
        # DCR_PROFILE_AT_STEP) armed a jax.profiler window over the next K
        # device steps
        # fast-sampling accounting: the plan is static per bucket, so the
        # denoiser-call reduction is known on the host without touching the
        # device. One sample/fast span per accelerated batch execution
        # (args.batch = trajectories in it) feeds trace_report's "Fast
        # sampling" section; dense-bucket traces keep their pre-fast shape.
        plan = fastsample.fast_plan(bucket.steps, bucket.fast_ratio)
        calls = fastsample.unet_calls(plan)
        fast_span = (tracing.span("sample/fast", steps=bucket.steps,
                                  unet_calls=calls, batch=n,
                                  fast_ratio=bucket.fast_ratio,
                                  fast_order=bucket.fast_order,
                                  sampler=bucket.sampler)
                     if calls < bucket.steps else contextlib.nullcontext())
        with profiling.capture():
            # dcr-hbm: hbm_peak/hbm_delta attrs on the device step (no-op
            # where the backend reports no memory stats)
            with tracing.span("serve/device_step", batch=n, request_ids=ids,
                              trace_ids=traces,
                              bucket=str(tuple(bucket))) as dsp, \
                    memwatch.span_hbm(dsp):
                with fast_span:
                    # np.asarray forces the transfer, so these spans close
                    # only when the device work is actually done — real
                    # step time, not dispatch
                    images = np.asarray(
                        fn(self.stack.params, cond, uncond, seeds))
        images = images[:n]
        # copy-risk scoring runs on the HOST COPY after the device step:
        # generation is already done, so images are bit-identical with
        # scoring on or off
        self._score_risk(requests, images, ids, traces)
        return images

    # -- the drain loop ------------------------------------------------------

    def _on_hang(self) -> None:
        from dcr_tpu.core.coordination import hang_abort

        hang_abort("serve_batch",
                   detail=f"sampler step exceeded {self.cfg.hang_timeout_s}s")

    def _inject_batch_faults(self, batch_index: int) -> None:
        """Serve-side deterministic fault hooks (utils/faults.py), fired
        inside the batch watchdog window so a wedge is caught by the same
        machinery a real one would be. ``worker_crash`` is a true SIGKILL —
        no drain, no flush, no exit handler — because that is the death a
        fleet supervisor must requeue around; ``worker_hang`` wedges this
        thread exactly like a dead collective; ``slow_step`` is a straggler
        (DCR_SLOW_STEP_S, default 30s) for latency/SLO chaos."""
        from dcr_tpu.utils import faults

        if faults.fire("worker_crash", batch=batch_index):
            os.kill(os.getpid(), signal.SIGKILL)
        if faults.fire("worker_hang", batch=batch_index):
            from dcr_tpu.core.coordination import simulate_hang

            simulate_hang(f"worker_hang@batch={batch_index}")
        if faults.fire("slow_step", batch=batch_index):
            time.sleep(float(os.environ.get("DCR_SLOW_STEP_S", "30")))
        if faults.fire("oom", batch=batch_index):
            # deterministic RESOURCE_EXHAUSTED through the real batch path:
            # _process's OOM catch dumps the memory-enriched flight recorder
            # and exits 85 — the typed death a fleet supervisor requeues
            # around with zero drops
            raise memwatch.InjectedOom(f"serve batch {batch_index}")

    def _process(self, batch: list[Request]) -> None:
        t0 = time.monotonic()
        now_wall = time.time()
        batch_index = self._batch_index
        self._batch_index += 1
        for req in batch:
            # queue wait measured from the admission stamp, recorded
            # retroactively under the request's root span: the number the
            # batcher's deadline policy is supposed to bound
            waited = t0 - req.enqueued_at
            tracing.complete_span(
                "serve/queue_wait", start_wall=now_wall - waited, dur_s=waited,
                parent=req.span.id if req.span is not None else None,
                trace=req.trace_id, request_id=req.id)
        try:
            # the watchdog turns a wedged device step into a structured
            # post-mortem + EXIT_HANG instead of a silently dead port
            with R.watchdog("serve:batch", self.cfg.hang_timeout_s,
                            on_timeout=self._on_hang):
                self._inject_batch_faults(batch_index)
                images = self.execute(batch)
        except Exception as e:
            if memwatch.is_oom_error(e):
                # dcr-hbm fatal path: the device allocator failed — this
                # process cannot promise any further batch, so die TYPED
                # (exit 85) with a memory-enriched post-mortem instead of
                # failing one batch and serving the next from a poisoned
                # allocator. In a fleet the supervisor requeues the
                # journaled in-flight requests onto survivors (zero drops);
                # futures are deliberately left for the death to break.
                with self._samplers_lock:
                    buckets = [tuple(b) for b in self._samplers]
                memwatch.oom_abort(
                    f"serve batch {batch_index} bucket {batch[0].bucket}",
                    e, buckets=buckets)
            R.log_event("serve_batch_failed", batch=len(batch),
                        bucket=str(batch[0].bucket), error=repr(e))
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(e)
            self.metrics.note_batch(len(batch), self.cfg.max_batch, ok=False)
            return
        now = time.monotonic()
        for req, img in zip(batch, images):
            self.metrics.latency.observe(now - req.enqueued_at)
            req.future.set_result(img)
        self.metrics.note_batch(len(batch), self.cfg.max_batch, ok=True)
        log.info("serve: batch of %d/%d in %.3fs (queue depth %d)",
                 len(batch), self.cfg.max_batch, now - t0, self.queue.depth())
        if self._writer is not None:
            try:
                snap = self.metrics.snapshot()
                cache = self.cache.stats()
                self._writer.scalars(snap["batches_total"], {
                    "serve/queue_depth": self.queue.depth(),
                    "serve/batch_occupancy": snap["batch_occupancy_last"],
                    "serve/cache_hit_rate": cache["hit_rate"],
                    "serve/latency_p50_ms": snap["latency_ms"]["p50"],
                    "serve/latency_p99_ms": snap["latency_ms"]["p99"],
                })
            except Exception as e:
                # telemetry must never stop serving (a full disk under
                # --logdir is not a generation failure) — the requests were
                # already answered above
                R.log_event("serve_metrics_write_failed", error=repr(e))
                R.bump_counter("serve_metrics_write_failed")

    def _run(self) -> None:
        while True:
            batch = self.batcher.next_batch(self.queue, stop=self._stop)
            if batch is None:
                break
            try:
                self._process(batch)
            except Exception as e:
                # last-resort guard: _process already converts generation
                # failures into per-request exceptions, so anything landing
                # here is a serving-layer bug — fail the batch's futures and
                # keep the port alive rather than dying silently with
                # /healthz still reporting ok
                R.log_event("serve_worker_error", error=repr(e),
                            batch=len(batch))
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)
        log.info("serve: worker drained and stopped")

    # -- on-demand device profiling ------------------------------------------

    def profile(self, body: dict) -> dict:
        """Arm a ``jax.profiler`` capture around the next K
        ``serve/device_step`` executions (``POST /debug/profile``). Body:
        ``{"steps"?: int, "logdir"?: str}``. Returns the armed status doc
        including the artifact directory the trace will land in; poll
        ``GET /debug/profile`` until ``artifact`` is set."""
        steps = int(body.get("steps", 1))
        logdir = body.get("logdir")
        if not logdir:
            base = tracing.trace_dir()
            if base is None:
                raise ValueError(
                    "no profile destination: pass 'logdir' or run the "
                    "worker with --logdir")
            logdir = str(base / "profile")
        return profiling.arm(logdir, steps)

    def profile_status(self) -> dict:
        return profiling.status()

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        """The /metrics document."""
        d = self.metrics.snapshot()
        d["queue_depth"] = self.queue.depth()
        d["draining"] = self.draining
        d["cache"] = self.cache.stats()
        risk = self._risk
        d["risk"] = {"status": self._risk_status,
                     "index_size": len(risk) if risk is not None else 0}
        if self._pump is not None:
            d["ingest"] = self._pump.stats()
        with self._samplers_lock:     # worker thread mutates concurrently
            d["compiled_buckets"] = [tuple(b) for b in self._samplers]
        return d

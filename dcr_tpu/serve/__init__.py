"""dcr-serve: the online generation service.

Layer map:

- :mod:`dcr_tpu.serve.queue` — bounded admission queue, typed overload/drain
  rejections, bucket-tagged requests;
- :mod:`dcr_tpu.serve.batcher` — deadline-aware dynamic batching (flush on
  full bucket or max-wait, immediate during drain);
- :mod:`dcr_tpu.serve.cache` — LRU prompt-embedding cache keyed on
  (tokenizer fingerprint, prompt, mitigation params);
- :mod:`dcr_tpu.serve.worker` — the resident core: per-bucket compiled
  samplers at a fixed padded batch shape, per-request PRNG keys, watchdog;
- :mod:`dcr_tpu.serve.server` — stdlib HTTP front end
  (POST /generate, GET /healthz, GET /metrics);
- :mod:`dcr_tpu.serve.fleet` — fleet control plane: heartbeat-leased worker
  membership plus the durable request journal (the zero-drop ledger);
- :mod:`dcr_tpu.serve.supervisor` — N device workers behind one front end:
  dispatch channels, requeue-on-death, respawn with backoff, SLO shedding.

Entry point: ``dcr-serve`` (:mod:`dcr_tpu.cli.serve`); ``--fleet.workers=N``
selects the supervisor role (README "Serving at scale"). SIGTERM stops
admission, finishes in-flight batches, and exits with
:data:`dcr_tpu.core.coordination.EXIT_PREEMPTED` (83).
"""

from dcr_tpu.serve.batcher import Batcher, should_flush
from dcr_tpu.serve.cache import EmbeddingCache, embedding_key, mitigation_tag
from dcr_tpu.serve.queue import (AdmissionError, BucketLimitError,
                                 DrainingError, GenBucket,
                                 InvalidRequestError, NoWorkersError,
                                 QueueFullError, Request, RequestQueue,
                                 SloShedError)
from dcr_tpu.serve.worker import (GenerationService, make_batch_sampler,
                                  validate_bucket)

__all__ = [
    "AdmissionError", "Batcher", "BucketLimitError", "DrainingError",
    "EmbeddingCache", "GenBucket", "GenerationService", "InvalidRequestError",
    "NoWorkersError", "QueueFullError", "Request", "RequestQueue",
    "SloShedError", "embedding_key", "make_batch_sampler", "mitigation_tag",
    "should_flush", "validate_bucket",
]

"""dcr-serve: the online generation service.

Layer map (all single-host, single-device-owner):

- :mod:`dcr_tpu.serve.queue` — bounded admission queue, typed overload/drain
  rejections, bucket-tagged requests;
- :mod:`dcr_tpu.serve.batcher` — deadline-aware dynamic batching (flush on
  full bucket or max-wait, immediate during drain);
- :mod:`dcr_tpu.serve.cache` — LRU prompt-embedding cache keyed on
  (tokenizer fingerprint, prompt, mitigation params);
- :mod:`dcr_tpu.serve.worker` — the resident core: per-bucket compiled
  samplers at a fixed padded batch shape, per-request PRNG keys, watchdog;
- :mod:`dcr_tpu.serve.server` — stdlib HTTP front end
  (POST /generate, GET /healthz, GET /metrics).

Entry point: ``dcr-serve`` (:mod:`dcr_tpu.cli.serve`). SIGTERM stops
admission, finishes in-flight batches, and exits with
:data:`dcr_tpu.core.coordination.EXIT_PREEMPTED` (83).
"""

from dcr_tpu.serve.batcher import Batcher, should_flush
from dcr_tpu.serve.cache import EmbeddingCache, embedding_key, mitigation_tag
from dcr_tpu.serve.queue import (AdmissionError, BucketLimitError,
                                 DrainingError, GenBucket,
                                 InvalidRequestError, QueueFullError, Request,
                                 RequestQueue)
from dcr_tpu.serve.worker import (GenerationService, make_batch_sampler,
                                  validate_bucket)

__all__ = [
    "AdmissionError", "Batcher", "BucketLimitError", "DrainingError",
    "EmbeddingCache", "GenBucket", "GenerationService", "InvalidRequestError",
    "QueueFullError", "Request", "RequestQueue", "embedding_key",
    "make_batch_sampler", "mitigation_tag", "should_flush", "validate_bucket",
]

"""Fleet supervisor: N device workers behind one front end, zero dropped
requests across worker death.

Topology (``dcr-serve --fleet.workers=N``)::

    supervisor process                         worker subprocess (xN)
    ------------------                         ----------------------
    HTTP front end (serve/server.py)           GenerationService (PR 4)
    bounded RequestQueue  <- admission         own HTTP server, port 0
    RequestJournal        <- zero-drop ledger  lease publish + heartbeat
    DispatchChannel xN    -> POST /generate_batch -> dynamic batching,
    monitor thread: leases, respawn, SLO          compiled samplers,
                                                  hang watchdog (exit 89)

The supervisor owns admission and accounting; workers own devices. A
dispatch channel pulls bucket-coherent batches from the shared queue (the
same :class:`~dcr_tpu.serve.batcher.Batcher` policy as single-process
serve) only while its worker is alive — per-worker flow control is the
channel itself, which keeps at most one batch in flight per worker, so the
in-flight set per worker is exactly one journal batch.

Failure model — every path ends in "requeue, respawn, keep serving":

- **crash** (SIGKILL, segfault, injected ``worker_crash``): the in-flight
  HTTP call breaks, the channel requeues the batch at the queue HEAD and the
  monitor respawns the worker with bounded exponential backoff;
- **hang** (injected ``worker_hang``, wedged device step): the worker's own
  batch watchdog exits 89; if that is disabled, the supervisor's
  ``fleet.dispatch_timeout_s`` expires, the worker is SIGKILLed, same path;
- **preemption** (external SIGTERM, exit 83): treated as a death — the
  worker drains what it holds, everything else requeues;
- **lease lapse** (process frozen but not dead): SIGKILL + requeue.

Requeue is SAFE to re-execute because PR 4 made every image a pure function
of (ckpt, prompt, seed, bucket) — a re-run on another worker is
bit-identical, and the journal's first-completion-wins ack means a client
never sees two answers. When queue-wait p99 (telemetry registry) breaches
``fleet.slo_queue_wait_p99_s`` with a real backlog, admission sheds typed
503s with Retry-After instead of quietly growing the queue. When every
worker slot exhausts its respawn budget the supervisor fails loudly: pending
futures get typed errors, the flight recorder dumps, and the front end
reports "failed".
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from dcr_tpu.core import resilience as R
from dcr_tpu.core import tracing
from dcr_tpu.core.config import ServeConfig, to_dict
from dcr_tpu.core.coordination import EXIT_OOM
from dcr_tpu.core.metrics import LatencyTracker
from dcr_tpu.obs.slo import SloEngine, default_objectives, parse_exposition
from dcr_tpu.serve.batcher import Batcher
from dcr_tpu.serve.fleet import (FleetPaths, RequestJournal, WorkerLease,
                                 clear_lease, fleet_paths, read_lease)
from dcr_tpu.serve.scrape import (ScrapeCache, http_get_text, inject_labels,
                                  merge_expositions)
from dcr_tpu.sampling import fastsample
from dcr_tpu.serve.queue import (AdmissionError, BucketLimitError,
                                 DrainingError, GenBucket, NoWorkersError,
                                 Request, RequestQueue, SloShedError)
from dcr_tpu.serve.worker import validate_bucket

# worker slot states
SPAWNING = "spawning"   # process launched, waiting for its lease
ALIVE = "alive"         # lease observed, dispatch channel running
BACKOFF = "backoff"     # died; respawn scheduled
RETIRED = "retired"     # respawn budget exhausted — slot permanently down


class RequestFailedError(RuntimeError):
    """A request exhausted its dispatch attempts (every attempt lost its
    worker) or its worker reported a per-request error — surfaced as the
    future's exception, mapped to HTTP 500 by the front end."""


# per-item worker errors (wire format "<TypeName>: <detail>") that describe
# the WORKER's state, not the request: re-execution on a survivor succeeds,
# so these requeue like a transport failure. Everything else (validation,
# generation failure) would fail identically anywhere and becomes a typed
# terminal failure.
_RETRYABLE_ITEM_PREFIXES = ("DrainingError:", "QueueFullError:")


def retryable_item_error(error: str) -> bool:
    return error.startswith(_RETRYABLE_ITEM_PREFIXES)


def _post_json(host: str, port: int, path: str, payload: dict,
               timeout_s: float) -> tuple[int, dict]:
    """One JSON POST over a fresh connection. The timeout is socket-level
    (connect + each read), which bounds a dead/wedged peer; a trickling peer
    is bounded by the worker's own watchdog instead."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        body = json.dumps(payload).encode()
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


class _WorkerSlot:
    """Mutable per-slot record; state transitions happen under the
    supervisor's lock (monitor thread and dispatch channels race on
    death-detection)."""

    def __init__(self, index: int):
        self.index = index
        self.state = BACKOFF                 # start() spawns immediately
        self.proc: Optional[subprocess.Popen] = None
        self.lease: Optional[WorkerLease] = None
        self.channel: Optional["DispatchChannel"] = None
        self.consecutive_failures = 0
        self.respawn_at = 0.0                # wall clock; 0 = due now
        self.spawn_deadline = 0.0
        self.alive_since = 0.0
        self.incarnation = 0                 # spawn count, for log lines

    def snapshot(self) -> dict:
        lease = self.lease
        return {
            "index": self.index, "state": self.state,
            "incarnation": self.incarnation,
            "pid": self.proc.pid if self.proc is not None else None,
            "port": lease.port if lease is not None else None,
            "lease_age_s": round(lease.age_s(), 3) if lease is not None else None,
            "consecutive_failures": self.consecutive_failures,
            # warm-start readiness from the lease payload: a SPAWNING slot
            # with ready=False is a live worker still compiling its warm plan
            "ready": self.state == ALIVE,
            "buckets_warm": lease.buckets_warm if lease is not None else None,
            "buckets_total": lease.buckets_total if lease is not None else None,
            "risk": lease.risk if lease is not None else None,
        }


def wire_item(req: Request, bucket: GenBucket, attempt: int) -> dict:
    """One ``/generate_batch`` wire item: prompt + seed + the FULL bucket
    identity — every field, including the fast-sampling plan, so the worker
    executes the supervisor's bucket rather than back-filling missing knobs
    from its own default — plus the distributed trace context. The worker
    side decodes it with ``server.request_bucket`` (round-trip pinned in
    tests/test_fastsample.py)."""
    return {"prompt": req.prompt, "seed": req.seed,
            "resolution": bucket.resolution, "steps": bucket.steps,
            "guidance": bucket.guidance, "sampler": bucket.sampler,
            "rand_noise_lam": bucket.rand_noise_lam,
            "fast_ratio": bucket.fast_ratio,
            "fast_order": bucket.fast_order,
            "trace": (tracing.wire_context(req.span, attempt)
                      if req.span is not None else None)}


class DispatchChannel:
    """The per-worker dispatch loop: pull a bucket-coherent batch from the
    shared queue, POST it to the worker, resolve futures from the response.
    One batch in flight at a time; any transport failure requeues the batch
    and reports the worker dead. The epilogue sweep requeues anything the
    journal still shows in flight on this worker — belt-and-braces against a
    channel dying between dispatch bookkeeping and the HTTP call."""

    def __init__(self, supervisor: "FleetSupervisor", slot: _WorkerSlot,
                 lease: WorkerLease):
        self.supervisor = supervisor
        self.slot = slot
        self.index = slot.index
        self.port = lease.port
        self._stop = threading.Event()
        # Event, not a bare bool: set by the monitor thread, read by the
        # dispatch loop — no shared lock covers the pair
        self._dead = threading.Event()       # set (pre-stop) on worker death
        cfg = supervisor.cfg
        self._batcher = Batcher(cfg.max_batch, cfg.max_wait_ms / 1000.0)
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"fleet-dispatch:{self.index}.{slot.incarnation}")

    def start(self) -> None:
        self._thread.start()

    def mark_dead(self) -> None:
        self._dead.set()
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()

    def finished(self) -> bool:
        return not self._thread.is_alive()

    def join(self, timeout_s: float) -> None:
        self._thread.join(timeout_s)

    # -- the loop ------------------------------------------------------------

    def _run(self) -> None:
        sup = self.supervisor
        try:
            while True:
                batch = self._batcher.next_batch(sup.queue, stop=self._stop)
                if batch is None:
                    break
                if self._dead.is_set():
                    # stop() raced the take: nothing was dispatched, so this
                    # is a plain reinsertion (journal state is still QUEUED)
                    sup.queue.requeue(batch)
                    break
                if not self._dispatch(batch):
                    break
        except Exception as e:
            # a channel bug must surface as a worker failure (requeue +
            # respawn), never a silently missing consumer
            R.log_event("fleet_channel_error", worker=self.index, error=repr(e))
            R.bump_counter("fleet_channel_errors")
            sup._worker_failed(self.slot, f"dispatch channel error: {e!r}")
        finally:
            sup._sweep_orphans(self.index)

    def _dispatch(self, batch: list[Request]) -> bool:
        sup = self.supervisor
        cfg = sup.cfg
        t0 = time.monotonic()
        now_wall = time.time()
        send: list[Request] = []
        attempts: dict[int, int] = {}
        for req in batch:
            attempt = sup.journal.dispatch(req.id, self.index)
            if attempt is None:
                continue    # completed via a duplicate path while queued
            attempts[req.id] = attempt
            waited = t0 - req.enqueued_at
            sup.metrics.queue_wait.observe(waited)
            tracing.complete_span(
                "serve/queue_wait", start_wall=now_wall - waited,
                dur_s=waited,
                parent=req.span.id if req.span is not None else None,
                trace=req.trace_id, request_id=req.id)
            send.append(req)
        if not send:
            return True
        b = send[0].bucket
        # each wire item carries its distributed trace context: the worker
        # parents its serve/request span on the supervisor's root, so one
        # request = one span tree across both processes — and a requeued
        # re-execution ships the same trace id with attempt+1, merging as a
        # sibling child of the same root
        payload = {"requests": [wire_item(r, b, attempts[r.id])
                                for r in send]}
        ids = [r.id for r in send]
        with tracing.span("fleet/dispatch", worker=self.index,
                          batch=len(send), request_ids=ids,
                          trace_ids=[r.trace_id for r in send]):
            try:
                status, doc = _post_json(
                    cfg.host, self.port, "/generate_batch", payload,
                    cfg.fleet.dispatch_timeout_s)
            except (OSError, ValueError, http.client.HTTPException) as e:
                sup._requeue(send, self.index, f"transport: {e!r}")
                sup._worker_failed(self.slot, f"dispatch failed: {e!r}")
                return False
        results = doc.get("results") if status == 200 else None
        if results is None or len(results) != len(send):
            sup._requeue(send, self.index,
                         f"bad dispatch response (status {status})")
            sup._worker_failed(
                self.slot, f"dispatch rejected: status {status} {doc!r}")
            return False
        retry: list[Request] = []
        retry_reason = ""
        for req, item in zip(send, results):
            err = item.get("error")
            if err is not None:
                if retryable_item_error(err):
                    # the worker rejected the item because of ITS state
                    # (SIGTERM drain, local overload) — survivors can serve
                    # it bit-identically; handled below, stays live
                    retry.append(req)
                    retry_reason = retry_reason or err
                    continue
                # a per-request error from a HEALTHY worker is not transient
                # (typed validation/generation failure) — retrying it
                # elsewhere would fail identically
                if sup.journal.fail(req.id, err):
                    sup.counter("failed").inc()
                    req.future.set_exception(RequestFailedError(err))
            else:
                if sup.journal.ack(req.id, self.index):
                    item["worker"] = self.index
                    req.future.set_result(item)
                    sup.counter("completed").inc()
                else:
                    sup.counter("duplicate_completions").inc()
            sup._finish(req.id)
        sup.counter("batches_dispatched").inc()
        if retry:
            # requeue FIRST (so the orphan sweep can't double-handle them),
            # then retire this worker from dispatch: a draining worker is
            # leaving membership, and redispatching to it from this channel
            # would burn the requests' attempt budget in a tight loop
            sup._requeue(retry, self.index,
                         f"worker rejected items: {retry_reason}",
                         charge=False)
            sup._worker_failed(
                self.slot,
                f"rejected {len(retry)} item(s): {retry_reason}")
            return False
        return True


class FleetSupervisor:
    """Front-end-facing service (duck-compatible with
    :class:`~dcr_tpu.serve.worker.GenerationService`: ``submit`` / ``status``
    / ``default_bucket`` / ``draining``) plus the worker lifecycle engine.
    ``serve/server.py``'s handler works against either."""

    def __init__(self, cfg: ServeConfig,
                 on_fatal: Optional[Callable[[], None]] = None):
        if cfg.fleet.workers < 1:
            raise ValueError("FleetSupervisor requires fleet.workers >= 1")
        self.cfg = cfg
        self.paths: FleetPaths = fleet_paths(cfg.fleet.dir).ensure()
        self.queue = RequestQueue(cfg.queue_depth)
        self.journal = RequestJournal(self.paths.journal)
        self.metrics = _FleetMetrics()
        self._on_fatal = on_fatal
        self._requests: dict[int, Request] = {}   # live until terminal
        self._requests_lock = threading.Lock()
        self._admitted_buckets: set[GenBucket] = set()
        self._buckets_lock = threading.Lock()
        self._vae_scale: Optional[int] = None     # learned from first lease
        # health stays "warming" until the first worker reports READY:
        # _vae_scale alone now arrives with the first warming (not-ready)
        # lease so admission can open and queue early, but a balancer must
        # not see "ok" while nothing can serve yet
        self._ever_ready = False
        # Event, not a bare bool: set by the front end's drain path, read
        # by admission and the monitor loop on their own threads
        self._draining = threading.Event()
        self._fatal = threading.Event()
        self._shutdown = threading.Event()
        self._lock = threading.Lock()             # slot state transitions
        self._slots = [_WorkerSlot(i) for i in range(cfg.fleet.workers)]
        self._poll_s = max(0.05, min(0.25, cfg.fleet.heartbeat_s / 2))
        self._healthy_reset_s = max(10.0, 5 * cfg.fleet.heartbeat_s)
        self._monitor: Optional[threading.Thread] = None
        self._scrape = ScrapeCache(cfg.host, cfg.fleet.scrape_timeout_s)
        self._scraper: Optional[threading.Thread] = None
        self._last_profile_worker: Optional[int] = None
        # dcr-slo: the declarative SLO engine rides the monitor loop; the
        # prev-counter snapshots turn lifetime counters into per-tick
        # deltas (a single shed burst must not latch the rate forever)
        self._slo = (SloEngine(cfg.slo, default_objectives(cfg))
                     if cfg.slo.enabled else None)
        self._slo_prev = {"accepted": 0.0, "shed": 0.0}
        self._slo_scrape_prev: dict[int, dict[str, float]] = {}

    def counter(self, name: str):
        return tracing.registry().counter(f"fleet/{name}")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        # one config file feeds every worker spawn: the full supervisor
        # config with the role fields overridden per spawn on the CLI
        self.paths.config.write_text(
            json.dumps(to_dict(self.cfg), indent=2, sort_keys=True) + "\n")
        for slot in self._slots:
            self._spawn(slot)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True, name="fleet-monitor")
        self._monitor.start()
        self._scraper = threading.Thread(target=self._scrape_loop,
                                         daemon=True, name="fleet-scraper")
        self._scraper.start()

    def _spawn(self, slot: _WorkerSlot) -> None:
        f = self.cfg.fleet
        clear_lease(self.paths, slot.index)   # a stale lease must never join
        with self._lock:
            slot.incarnation += 1
            incarnation = slot.incarnation
        argv = [sys.executable, "-m", "dcr_tpu.cli.serve",
                f"--config={self.paths.config}",
                "--fleet.workers=0",
                f"--fleet.worker_index={slot.index}",
                "--port=0"]
        env = dict(os.environ)
        # the `rank` fault coordinate of serve-side DCR_FAULTS kinds (also
        # keys the worker's flightrec_w<i>_<rank>.json dump name)
        env["DCR_WORKER_INDEX"] = str(slot.index)
        # fallback post-mortem destination for workers running without a
        # --logdir: all workers share the fleet dir, so the worker-indexed
        # dump name above is what keeps one crash from clobbering another's
        env.setdefault("DCR_FLIGHTREC_DIR", str(self.paths.root))
        try:
            with open(self.paths.worker_log(slot.index), "ab") as logf:
                # Popen itself runs outside the lock (fork/exec is slow);
                # only the slot-state publish is guarded
                proc = subprocess.Popen(argv, stdout=logf,
                                        stderr=subprocess.STDOUT, env=env)
        except OSError as e:
            R.log_event("fleet_spawn_error", worker=slot.index, error=repr(e))
            R.bump_counter("fleet_spawn_errors")
            self._spawn_failed(slot, f"spawn: {e!r}")
            return
        with self._lock:
            slot.proc = proc
            slot.state = SPAWNING
            slot.spawn_deadline = time.time() + f.spawn_timeout_s
        self.counter("workers_spawned").inc()
        R.log_trace("fleet_spawn", worker=slot.index, pid=proc.pid,
                    incarnation=incarnation)

    def _worker_joined(self, slot: _WorkerSlot, lease: WorkerLease) -> None:
        with self._lock:
            if slot.state != SPAWNING:
                return
            slot.state = ALIVE
            slot.lease = lease
            slot.alive_since = time.time()
            self._ever_ready = True
            if self._vae_scale is None:
                self._vae_scale = lease.vae_scale
            slot.channel = DispatchChannel(self, slot, lease)
        slot.channel.start()
        R.log_trace("fleet_worker_joined", worker=slot.index, pid=lease.pid,
                    port=lease.port, incarnation=slot.incarnation)

    def _schedule_backoff_locked(self, slot: _WorkerSlot) -> bool:
        """One failure tick (caller holds ``self._lock``): bump the streak,
        move the slot to BACKOFF with bounded exponential delay — or RETIRED
        past the respawn budget. Returns whether the slot retired. The ONLY
        place the backoff/retire policy lives; runtime deaths and spawn
        failures must never drift apart."""
        f = self.cfg.fleet
        slot.consecutive_failures += 1
        delay = min(f.respawn_max_delay_s,
                    f.respawn_base_delay_s
                    * (2 ** (slot.consecutive_failures - 1)))
        slot.respawn_at = time.time() + delay
        retire = slot.consecutive_failures > f.respawn_max
        slot.state = RETIRED if retire else BACKOFF
        if retire:
            # a permanently-down slot must not keep serving its last scraped
            # numbers forever from the merged /metrics; the up/staleness
            # gauges still report the slot itself as down
            self._scrape.forget(slot.index)
        return retire

    def _worker_failed(self, slot: _WorkerSlot, reason: str) -> None:
        """First caller wins (monitor vs dispatch channel race); moves the
        slot to BACKOFF (or RETIRED), kills any remaining process, and lets
        the channel's error path / epilogue sweep requeue the in-flight
        work."""
        with self._lock:
            if slot.state not in (ALIVE, SPAWNING):
                return
            proc, channel = slot.proc, slot.channel
            rc = proc.poll() if proc is not None else None
            slot.lease = None
            retire = self._schedule_backoff_locked(slot)
            failures = slot.consecutive_failures
        self.counter("workers_lost").inc()
        R.log_event("fleet_worker_lost", worker=slot.index, reason=reason,
                    rc=rc, consecutive_failures=failures,
                    retired=retire)
        if channel is not None:
            channel.mark_dead()
        if proc is not None and proc.poll() is None:
            # frozen or wedged, not dead: SIGKILL also breaks the channel's
            # in-flight HTTP call, which is what triggers the requeue
            try:
                proc.kill()
            except OSError as e:
                R.log_event("fleet_kill_error", worker=slot.index,
                            error=repr(e))
                R.bump_counter("fleet_kill_errors")
        clear_lease(self.paths, slot.index)
        if retire:
            R.log_event("fleet_slot_retired", worker=slot.index,
                        failures=failures)

    def _spawn_failed(self, slot: _WorkerSlot, reason: str) -> None:
        with self._lock:
            proc = slot.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError as e:
                R.log_event("fleet_kill_error", worker=slot.index,
                            error=repr(e))
                R.bump_counter("fleet_kill_errors")
        with self._lock:
            slot.lease = None    # a warming (not-ready) lease may be attached
            retire = self._schedule_backoff_locked(slot)
        R.log_event("fleet_spawn_failed", worker=slot.index, reason=reason,
                    retired=retire)

    @staticmethod
    def _rc_reason(rc: int) -> str:
        """Name the typed exit codes in death reasons: an OOM (85) is
        handled exactly like any crash — requeue + respawn — but the
        operator-facing reason should say where the post-mortem is."""
        if rc == EXIT_OOM:
            return (f"worker OOM (exit {rc} EXIT_OOM — its flight-recorder "
                    "dump carries the memory snapshot and live-surface "
                    "footprints)")
        return f"process exited rc={rc}"

    def _monitor_loop(self) -> None:
        while not self._shutdown.wait(self._poll_s):
            now = time.time()
            alive = 0
            for slot in self._slots:
                # snapshot the slot under the lock, act on the copy: the
                # branch bodies re-check state under the lock before any
                # dependent write, so a stale snapshot costs one poll tick,
                # never a lost transition
                with self._lock:
                    state = slot.state
                    proc = slot.proc
                    spawn_deadline = slot.spawn_deadline
                    respawn_at = slot.respawn_at
                    channel = slot.channel
                    failures = slot.consecutive_failures
                if state == ALIVE:
                    rc = proc.poll()
                    lease = read_lease(self.paths, slot.index)
                    if rc is not None:
                        self._worker_failed(slot, self._rc_reason(rc))
                    elif lease is None or lease.expired(now):
                        age = lease.age_s(now) if lease is not None else None
                        self._worker_failed(
                            slot, f"lease lapsed (age {age}s) — frozen worker")
                    else:
                        # re-check under the lock: a dispatch channel may
                        # have moved the slot to BACKOFF since the unlocked
                        # state read above — writing lease/streak then would
                        # pin a live-looking lease onto a dead slot and lose
                        # a failure increment
                        with self._lock:
                            if slot.state == ALIVE:
                                slot.lease = lease
                                alive += 1
                                if (slot.consecutive_failures
                                        and now - slot.alive_since
                                        > self._healthy_reset_s):
                                    slot.consecutive_failures = 0
                elif state == SPAWNING:
                    rc = proc.poll()
                    lease = read_lease(self.paths, slot.index)
                    ours = lease is not None and lease.pid == proc.pid
                    if ours and lease.ready:
                        # dispatch is gated on READINESS, not liveness: a
                        # worker publishes its lease with ready=False while
                        # its warm plan compiles, and the channel only
                        # attaches once the lease reports ready — the
                        # supervisor never dispatches into a cold worker
                        self._worker_joined(slot, lease)
                        alive += 1
                    elif rc is not None:
                        self._spawn_failed(
                            slot, f"{self._rc_reason(rc)} before publishing "
                            "a ready lease")
                    elif now > spawn_deadline:
                        self._spawn_failed(slot, "no ready lease within "
                                           f"{self.cfg.fleet.spawn_timeout_s}s"
                                           " (spawn_timeout_s covers load + "
                                           "warm start)")
                    elif ours:
                        # warming: surface progress in status() and learn the
                        # model's vae scale early so admission can open (and
                        # queue) while the first worker is still compiling
                        with self._lock:
                            if slot.state == SPAWNING:
                                slot.lease = lease
                                if self._vae_scale is None:
                                    self._vae_scale = lease.vae_scale
                elif state == BACKOFF:
                    channel_done = (channel is None
                                    or channel.finished())
                    # a drain suppresses respawns ONLY once the backlog is
                    # gone: if the last worker dies mid-drain with accepted
                    # requests still requeued, refusing to respawn would
                    # strand them until the shutdown timeout 500s them —
                    # breaking "every accepted request receives its response"
                    if (channel_done and now >= respawn_at
                            and (not self._draining.is_set()
                                 or self.journal.pending_count() > 0)):
                        # the old incarnation's channel has fully unwound
                        # (its orphan sweep ran), so requeue/dispatch can't
                        # race the fresh incarnation
                        with tracing.span("fleet/respawn", worker=slot.index,
                                          failures=failures):
                            self.counter("respawns").inc()
                            self._spawn(slot)
            tracing.registry().gauge("fleet/workers_alive").set(float(alive))
            self._update_slo_gauges(alive)
            if self._slo is not None:
                try:
                    self._slo.observe(self._slo_signals())
                except Exception as e:
                    # evaluation is observability; the monitor loop is the
                    # fleet's heartbeat — log the failure, keep monitoring
                    R.log_event("slo_observe_failed", error=repr(e))
                    R.bump_counter("slo_observe_errors")
            with self._lock:
                all_retired = all(s.state == RETIRED for s in self._slots)
            if alive == 0 and all_retired and not self._fatal.is_set():
                self._fail_fleet()

    def _update_slo_gauges(self, alive: int) -> None:
        """Fleet SLO series as first-class exported gauges (scraped via
        /metrics?format=prometheus) instead of log lines: queue-wait p99 vs
        its target, shed rate, requeue rate, availability."""
        reg = tracing.registry()
        f = self.cfg.fleet
        reg.gauge("fleet/availability").set(alive / max(1, len(self._slots)))
        reg.gauge("fleet/queue_wait_p99_s").set(
            self.metrics.queue_wait.percentiles((99,))["p99"])
        reg.gauge("fleet/slo_queue_wait_p99_s").set(f.slo_queue_wait_p99_s)
        counts = reg.counters("fleet/")
        accepted = counts.get("fleet/accepted", 0)
        shed = counts.get("fleet/shed", 0)
        reg.gauge("fleet/shed_rate").set(shed / max(1, accepted + shed))
        reg.gauge("fleet/requeue_rate").set(
            counts.get("fleet/requeued", 0) / max(1, accepted))

    # -- dcr-slo: objective signals + engine access ---------------------------

    def _fresh_worker_metrics(self) -> dict[int, dict[str, float]]:
        """Parsed metric dicts for every ALIVE worker whose cached scrape is
        FRESH (same staleness rule as ``dcr_fleet_worker_up``). A stale or
        missing scrape excludes the worker entirely — the SLO plane judges
        what it can still see, never a dead worker's last-good numbers."""
        f = self.cfg.fleet
        stale_after = (3 * max(f.scrape_period_s, f.scrape_timeout_s)
                       + len(self._slots) * f.scrape_timeout_s)
        scraped = self._scrape.snapshot()
        with self._lock:
            alive_idx = [s.index for s in self._slots if s.state == ALIVE]
        out: dict[int, dict[str, float]] = {}
        for index in alive_idx:
            text_age = scraped.get(index)
            if text_age is not None and text_age[1] <= stale_after:
                out[index] = parse_exposition(text_age[0])
        return out

    def _slo_signals(self) -> dict:
        """One signal snapshot per monitor tick for :meth:`SloEngine.observe`.
        Rates come from per-tick counter DELTAS (lifetime ratios latch old
        incidents forever); absent planes report None (no sample), never a
        fake healthy value."""
        workers = self._fresh_worker_metrics()
        signals: dict = {
            "availability": len(workers) / max(1, len(self._slots)),
            "queue_wait_p99_s":
                self.metrics.queue_wait.percentiles((99,))["p99"],
        }
        counts = tracing.registry().counters("fleet/")
        accepted = float(counts.get("fleet/accepted", 0))
        shed = float(counts.get("fleet/shed", 0))
        d_acc = accepted - self._slo_prev["accepted"]
        d_shed = shed - self._slo_prev["shed"]
        self._slo_prev.update(accepted=accepted, shed=shed)
        signals["shed_rate"] = (d_shed / (d_acc + d_shed)
                                if (d_acc + d_shed) > 0 else None)
        lag = [max(m.get("dcr_ingest_lag_seconds", 0.0),
                   m.get("dcr_ingest_oldest_unfolded_age_s", 0.0))
               for m in workers.values()
               if "dcr_ingest_lag_seconds" in m
               or "dcr_ingest_oldest_unfolded_age_s" in m]
        signals["ingest_lag_s"] = max(lag) if lag else None
        stale = [m["dcr_ann_staleness_rows"] for m in workers.values()
                 if "dcr_ann_staleness_rows" in m]
        signals["ann_staleness_rows"] = max(stale) if stale else None
        # online recall: sample-weighted across workers — a worker with 64
        # probed samples outweighs one that has probed twice
        num = den = 0.0
        for m in workers.values():
            n = m.get("dcr_ann_recall_online_samples", 0.0)
            if n > 0 and "dcr_ann_recall_online_pct" in m:
                num += (m["dcr_ann_recall_online_pct"] / 100.0) * n
                den += n
        signals["recall"] = (num / den) if den > 0 else None
        # coverage: scored/completed per tick, summed across workers; a
        # counter that moved backwards is a restarted worker — clamp its
        # delta to the fresh lifetime value instead of going negative
        d_scored = d_done = 0.0
        for index, m in workers.items():
            prev = self._slo_scrape_prev.get(index, {})
            for key, bucket in (("dcr_copy_risk_scored_total", "scored"),
                                ("dcr_serve_completed_total", "done")):
                cur = m.get(key)
                if cur is None:
                    continue
                delta = cur - prev.get(key, 0.0)
                if delta < 0:
                    delta = cur
                if bucket == "scored":
                    d_scored += delta
                else:
                    d_done += delta
            self._slo_scrape_prev[index] = {
                k: m[k] for k in ("dcr_copy_risk_scored_total",
                                  "dcr_serve_completed_total") if k in m}
        signals["coverage"] = (min(1.0, d_scored / d_done)
                               if d_done > 0 else None)
        return signals

    def slo_doc(self) -> dict:
        """``GET /slo``: the engine's full objective document (also the
        ``dcr-status`` payload)."""
        if self._slo is None:
            return {"enabled": False}
        return self._slo.doc()

    # -- fleet metrics aggregation -------------------------------------------

    def _scrape_loop(self) -> None:
        """Pull each live worker's full telemetry registry (Prometheus text
        on its internal port) into the last-good cache. Bounded per-target
        timeout: a dead/wedged worker costs one socket timeout per cycle,
        never a hang — and its last good section keeps serving with a
        growing staleness gauge."""
        period = self.cfg.fleet.scrape_period_s
        while not self._shutdown.wait(period):
            # snapshot (slot, lease) pairs under the lock — the monitor
            # writes slot.lease under it — then scrape outside the lock so
            # a slow target never stalls state transitions
            with self._lock:
                targets = [(slot, slot.lease) for slot in self._slots
                           if slot.state == ALIVE and slot.lease is not None]
            for slot, lease in targets:
                ok = self._scrape.scrape(slot.index, lease.port)
                # close the scrape/retire race: a GET in flight when the
                # monitor retires the slot (and forgets its section)
                # would otherwise re-insert the dead worker's metrics
                # with nothing left to ever clear them
                if ok:
                    with self._lock:
                        if slot.state == RETIRED:
                            self._scrape.forget(slot.index)

    def prometheus_merged(self) -> str:
        """The fleet-wide ``/metrics?format=prometheus`` document: the
        supervisor's own registry (admission, journal, SLO gauges) plus every
        worker's scraped registry with a ``worker="N"`` label on each series,
        plus per-worker up/staleness gauges. Built entirely from cached
        scrapes — never blocks on a worker."""
        status_doc = dict(self.status())
        for key in ("workers", "role", "health"):   # non-numeric
            status_doc.pop(key, None)
        tracing.update_gauges(status_doc, prefix="serve/")
        sections = [tracing.registry().prometheus_text()]
        scraped = self._scrape.snapshot()
        # staleness threshold is CYCLE-aware: the scrape loop is sequential,
        # so one full cycle can cost period + one timeout per wedged worker —
        # a fixed multiple of the period alone would flap worker_up to 0 on
        # healthy workers whenever siblings are timing out. A truly dead
        # worker still drops out of `up` immediately via slot.state.
        f = self.cfg.fleet
        stale_after = (3 * max(f.scrape_period_s, f.scrape_timeout_s)
                       + len(self._slots) * f.scrape_timeout_s)
        up_lines = [
            "# HELP dcr_fleet_worker_up 1 when the slot is ALIVE and its "
            "last scrape is fresh",
            "# TYPE dcr_fleet_worker_up gauge",
            "# HELP dcr_fleet_worker_scrape_age_seconds age of the worker's "
            "last successful registry scrape",
            "# TYPE dcr_fleet_worker_scrape_age_seconds gauge",
        ]
        with self._lock:
            slot_states = [(s.index, s.state) for s in self._slots]
        for index, state in slot_states:
            label = {"worker": str(index)}
            text_age = scraped.get(index)
            fresh = text_age is not None and text_age[1] <= stale_after
            up = 1 if (state == ALIVE and fresh) else 0
            up_lines.append(inject_labels(
                f"dcr_fleet_worker_up {up}", label).rstrip("\n"))
            if text_age is not None:
                up_lines.append(inject_labels(
                    f"dcr_fleet_worker_scrape_age_seconds "
                    f"{round(text_age[1], 3)}", label).rstrip("\n"))
                sections.append(inject_labels(text_age[0], label))
        sections.insert(1, "\n".join(up_lines) + "\n")
        return merge_expositions(sections)

    # -- on-demand device profiling ------------------------------------------

    def profile(self, body: dict) -> dict:
        """``POST /debug/profile`` routed to a worker: arm a jax.profiler
        capture around that worker's next K device steps. Body
        ``{"worker"?: int, "steps"?: int, "logdir"?: str}``; default target
        is the first ALIVE worker."""
        target = body.get("worker")
        with self._lock:
            alive = {s.index: s.lease for s in self._slots
                     if s.state == ALIVE and s.lease is not None}
        if target is None:
            if not alive:
                raise NoWorkersError("no ALIVE worker to profile")
            target = min(alive)
        target = int(target)
        if target not in alive:
            raise ValueError(f"worker {target} is not ALIVE "
                             f"(alive: {sorted(alive)})")
        fwd = {k: body[k] for k in ("steps", "logdir") if k in body}
        status, doc = _post_json(self.cfg.host, alive[target].port,
                                 "/debug/profile", fwd,
                                 self.cfg.fleet.scrape_timeout_s)
        if status != 200:
            raise RuntimeError(
                f"worker {target} rejected profile arm ({status}): {doc!r}")
        self._last_profile_worker = target
        return {**doc, "worker": target}

    def profile_status(self) -> dict:
        """``GET /debug/profile``: the armed worker's capture status."""
        target = self._last_profile_worker
        if target is None:
            return {"armed": False, "worker": None}
        with self._lock:
            slot = self._slots[target]
            lease = slot.lease if slot.state == ALIVE else None
        if lease is None:
            return {"armed": False, "worker": target,
                    "error": f"worker {target} is no longer alive"}
        try:
            status, text = http_get_text(self.cfg.host, lease.port,
                                         "/debug/profile",
                                         self.cfg.fleet.scrape_timeout_s)
            doc = json.loads(text) if status == 200 else {"error": text}
        except (OSError, ValueError, http.client.HTTPException) as e:
            doc = {"armed": False, "error": repr(e)}
        return {**doc, "worker": target}

    # -- copy-risk (dcr-watch) -----------------------------------------------

    def risk_health(self) -> str:
        """Fleet-level risk-index state for /healthz: "ok" once ANY alive
        worker can score (POST /check routes there), "failed" when every
        reporting worker failed its load — a fleet silently serving
        unscored is exactly what this field makes visible. Only ALIVE
        slots count, matching :meth:`check`'s routing filter exactly: a
        warming worker whose background index load finished early must
        not flip this to "ok" while /check still has nowhere to route."""
        if not self.cfg.risk.index_path:
            return "absent"
        with self._lock:
            statuses = [s.lease.risk for s in self._slots
                        if s.state == ALIVE and s.lease is not None]
        if "ok" in statuses:
            return "ok"
        if "loading" in statuses or not statuses:
            return "loading"
        return "failed"

    def check(self, body: dict) -> dict:
        """``POST /check`` routed to the first ALIVE worker whose lease
        reports a loaded risk index; the reply carries the serving worker's
        index. Raises RiskUnavailableError (503 + status) when no worker
        can answer."""
        from dcr_tpu.obs.copyrisk import RiskUnavailableError

        status = self.risk_health()
        with self._lock:
            ready = [(s.index, s.lease) for s in self._slots
                     if s.state == ALIVE and s.lease is not None
                     and s.lease.risk == "ok"]
        if not ready:
            raise RiskUnavailableError(
                f"no ALIVE worker with a loaded risk index "
                f"(fleet risk: {status})", status=status)
        last_err: Optional[BaseException] = None
        for index, lease in ready:
            try:
                code, doc = _post_json(self.cfg.host, lease.port, "/check",
                                       body,
                                       self.cfg.fleet.dispatch_timeout_s)
            except (OSError, ValueError, http.client.HTTPException) as e:
                # the crash race the fleet is BUILT for: the chosen worker
                # died between the lease read and the POST — fail over to
                # the next ready lease instead of 500ing a query another
                # worker can answer (the monitor reaps the dead one)
                R.log_event("risk_check_transport_error", worker=index,
                            error=repr(e))
                R.bump_counter("fleet_check_transport_errors")
                last_err = e
                continue
            if code == 400:
                raise ValueError(str(doc.get("error", doc)))
            if code == 503:
                # the worker's own risk state regressed (e.g. restarted and
                # reloading); stale-lease race — try the next ready worker
                last_err = RiskUnavailableError(
                    str(doc.get("detail", doc)),
                    status=doc.get("risk", status))
                continue
            if code != 200:
                raise RuntimeError(
                    f"worker {index} rejected /check ({code}): {doc!r}")
            return {**doc, "worker": index}
        if isinstance(last_err, RiskUnavailableError):
            raise last_err
        raise RiskUnavailableError(
            f"every risk-ready worker failed the check query "
            f"(last: {last_err!r})", status=status)

    def _fail_fleet(self) -> None:
        """Every slot exhausted its respawn budget: fail pending work loudly
        and leave a post-mortem, instead of a healthy-looking port whose
        queue never drains."""
        self._fatal.set()
        R.log_event("fleet_failed", workers=self.cfg.fleet.workers,
                    pending=self.journal.pending_count())
        with self._requests_lock:
            pending = list(self._requests.values())
        for req in pending:
            if self.journal.fail(req.id, "fleet failed: all slots retired"):
                self.counter("failed").inc()
                if not req.future.done():
                    req.future.set_exception(RequestFailedError(
                        "fleet failed: every worker slot exhausted its "
                        "respawn budget"))
            self._finish(req.id)
        tracing.dump_flight_recorder("fleet_failed: all worker slots retired")
        if self._on_fatal is not None:
            self._on_fatal()

    # -- requeue / bookkeeping ----------------------------------------------

    def _requeue(self, reqs: list[Request], worker: int, reason: str,
                 charge: bool = True) -> None:
        """Journaled IN_FLIGHT -> QUEUED for a dead worker's batch; requests
        past the attempt budget become typed failures instead (still never a
        silent drop — the journal records which). ``charge=False`` refunds
        the dispatch (worker-state rejection: the request never executed),
        so a rolling restart can't exhaust a request's budget with bounces
        that a survivor would serve identically."""
        keep: list[Request] = []
        with tracing.span("serve/requeue", worker=worker, n=len(reqs),
                          reason=reason,
                          trace_ids=[r.trace_id for r in reqs]):
            for req in reqs:
                attempts = self.journal.requeue(req.id, worker, reason,
                                                charge=charge)
                if attempts >= self.cfg.fleet.max_attempts:
                    if self.journal.fail(
                            req.id, f"attempts exhausted ({attempts})"):
                        self.counter("failed").inc()
                        if not req.future.done():
                            req.future.set_exception(RequestFailedError(
                                f"request lost its worker {attempts} times "
                                f"(last: {reason})"))
                    self._finish(req.id)
                else:
                    keep.append(req)
                    self.counter("requeued").inc()
            self.queue.requeue(keep)
        R.log_event("serve_requeue", worker=worker, n=len(keep),
                    failed=len(reqs) - len(keep), reason=reason)

    def _sweep_orphans(self, worker: int) -> None:
        """Requeue whatever the journal still shows in flight on a stopped
        worker — normally empty (the channel's error path already ran)."""
        ids = self.journal.inflight_for(worker)
        if not ids:
            return
        with self._requests_lock:
            reqs = [self._requests[i] for i in ids if i in self._requests]
        if reqs:
            self._requeue(reqs, worker, "orphan sweep after worker loss")

    def _finish(self, req_id: int) -> None:
        with self._requests_lock:
            self._requests.pop(req_id, None)

    # -- admission (front-end facing) ----------------------------------------

    def default_bucket(self) -> GenBucket:
        c = self.cfg
        ratio, order = fastsample.canonical_plan_params(
            c.num_inference_steps,
            c.fast.reuse_ratio if c.fast.enabled else 0.0, c.fast.order)
        return GenBucket(resolution=c.resolution, steps=c.num_inference_steps,
                         guidance=c.guidance_scale, sampler=c.sampler,
                         rand_noise_lam=c.rand_noise_lam,
                         fast_ratio=ratio, fast_order=order)

    def _check_shed(self) -> None:
        f = self.cfg.fleet
        if f.slo_queue_wait_p99_s <= 0:
            return
        # shedding needs BOTH a breached p99 and a live backlog: the p99
        # window only refreshes while requests flow, so without the depth
        # gate a single bad burst would latch the shed forever
        if self.queue.depth() < self.cfg.max_batch:
            return
        p99 = self.metrics.queue_wait.percentiles((99,)).get("p99", 0.0)
        if p99 > f.slo_queue_wait_p99_s:
            self.counter("shed").inc()
            raise SloShedError(
                f"queue-wait p99 {p99:.2f}s over SLO "
                f"{f.slo_queue_wait_p99_s:.2f}s — shedding",
                retry_after_s=f.shed_retry_after_s)

    def submit(self, prompt: str, *, seed: int = 0,
               bucket: Optional[GenBucket] = None,
               trace_ctx: Optional[dict] = None) -> Request:
        """Admit into the fleet queue. Same typed-rejection contract as
        GenerationService.submit, plus :class:`SloShedError` (503 +
        Retry-After) and :class:`NoWorkersError` (fleet warming/failed).
        ``trace_ctx`` exists for signature duck-compat with
        GenerationService; a supervisor is the trace ROOT, so an incoming
        context is ignored (fleets do not nest)."""
        del trace_ctx
        f = self.cfg.fleet
        bucket = bucket or self.default_bucket()
        try:
            if self._draining.is_set():
                raise DrainingError(
                    "service is draining; not accepting requests")
            if self._fatal.is_set():
                raise NoWorkersError(
                    "fleet failed: every worker slot is retired",
                    retry_after_s=f.shed_retry_after_s)
            with self._lock:   # published by the monitor under the same lock
                vae_scale = self._vae_scale
            if vae_scale is None:
                raise NoWorkersError(
                    "no worker has joined yet (fleet warming up)",
                    retry_after_s=f.shed_retry_after_s)
            validate_bucket(bucket, vae_scale=vae_scale)
            self._check_shed()      # before the bucket is registered
            with self._buckets_lock:
                bucket_added = bucket not in self._admitted_buckets
                if (bucket_added and len(self._admitted_buckets)
                        >= self.cfg.max_compiled_buckets):
                    raise BucketLimitError(
                        f"bucket {bucket} would exceed the resident "
                        f"compiled-sampler budget "
                        f"({self.cfg.max_compiled_buckets}) on every worker")
                self._admitted_buckets.add(bucket)
            req = Request(prompt=prompt, seed=int(seed) & 0xFFFFFFFF,
                          bucket=bucket)
            # the distributed-trace root: the id travels with the request
            # through the journal and every dispatched batch, and survives
            # requeue-after-worker-death unchanged (attempts become sibling
            # child spans under this root)
            req.trace_id = tracing.new_trace_id()
            root = tracing.begin_span("serve/request", parent=None,
                                      trace=req.trace_id,
                                      request_id=req.id, seed=req.seed,
                                      bucket=str(tuple(bucket)))
            req.span = root
            with self._requests_lock:
                self._requests[req.id] = req
            # journal BEFORE queue: a dispatch channel may pop the request
            # the instant it is published, and must find it journaled
            self.journal.add(req)
            try:
                self.queue.submit(req)
            except AdmissionError:
                self.journal.reject(req.id, "queue rejected admission")
                self._finish(req.id)
                # a never-dispatched novel bucket must not consume a
                # compiled-sampler slot forever. Kept when any live request
                # still carries it (the rare concurrent-admit race then at
                # worst over-counts by the one slot we leave registered)
                if bucket_added:
                    with self._requests_lock:
                        in_use = any(r.bucket == bucket
                                     for r in self._requests.values())
                    if not in_use:
                        with self._buckets_lock:
                            self._admitted_buckets.discard(bucket)
                raise
            if self._fatal.is_set():
                # raced _fail_fleet: its one-shot sweep may have snapshotted
                # _requests before this insert, leaving a request no retired
                # channel will ever pop and no sweep will ever fail. Make it
                # terminal here and reject admission with the same typed 503
                # the pre-check gives.
                try:
                    self.journal.reject(req.id, "fleet failed during admission")
                except ValueError:
                    pass            # the sweep got there first: already terminal
                self._finish(req.id)
                raise NoWorkersError(
                    "fleet failed: every worker slot is retired",
                    retry_after_s=f.shed_retry_after_s)
        except AdmissionError as e:
            self.metrics.note_rejected(e)
            tracing.event("serve/rejected", error=type(e).__name__)
            raise
        self.counter("accepted").inc()
        enq = req.enqueued_at
        req.future.add_done_callback(
            lambda fut: self._request_done(root, enq, fut))
        return req

    def _request_done(self, root, enqueued_at: float, fut) -> None:
        if fut.exception() is not None:
            root.end(error=repr(fut.exception()))
        else:
            self.metrics.latency.observe(time.monotonic() - enqueued_at)
            root.end()

    # -- drain / shutdown ----------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def fatal(self) -> bool:
        """True once every worker slot retired and pending work was failed —
        the front end should exit nonzero, not 83-restart-me."""
        return self._fatal.is_set()

    def health(self) -> str:
        if self._fatal.is_set():
            return "failed"
        if self._draining.is_set():
            return "draining"
        with self._lock:   # written by the monitor thread under the same lock
            vae_scale, ever_ready = self._vae_scale, self._ever_ready
        if vae_scale is None or not ever_ready:
            # cold boot: no worker has EVER reached ready — "warming" even
            # though admission may already be queueing. (After first ready,
            # transient all-workers-down churn keeps reporting "ok" exactly
            # as before dcr-warm: respawn is in flight, the queue holds.)
            return "warming"
        return "ok"

    def health_doc(self) -> dict:
        """The /healthz document: overall status plus worker readiness and
        the fleet's aggregate warm-bucket counts (from lease payloads)."""
        with self._lock:
            ready = sum(1 for s in self._slots if s.state == ALIVE)
            leases = [s.lease for s in self._slots if s.lease is not None]
        return {
            "status": self.health(),
            "workers_ready": ready,
            "workers_total": len(self._slots),
            "buckets_warm": sum(max(0, l.buckets_warm) for l in leases),
            "buckets_total": sum(max(0, l.buckets_total) for l in leases),
            "risk": self.risk_health(),
        }

    def begin_drain(self) -> None:
        """Stop admission. The shared queue is NOT closed: requeues of
        already-accepted work must keep landing while channels drain the
        backlog."""
        self._draining.set()
        R.log_trace("fleet_drain_begin", pending=self.journal.pending_count())

    def join_drained(self, timeout_s: float) -> bool:
        """Wait until every accepted request reached a terminal state."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.journal.pending_count() == 0:
                return True
            if self._fatal.is_set():
                return self.journal.pending_count() == 0
            time.sleep(self._poll_s)
        return self.journal.pending_count() == 0

    def shutdown(self, timeout_s: float = 60.0) -> None:
        """Stop channels, SIGTERM workers (their own drain -> exit 83), then
        reap. Call after :meth:`join_drained`; anything still pending at
        this point gets a typed failure, not silence."""
        self._shutdown.set()
        # snapshot channels/procs under the lock once: the monitor thread
        # may still be mid-tick attaching a channel when shutdown starts
        with self._lock:
            channels = [s.channel for s in self._slots]
            procs = [(s.index, s.proc) for s in self._slots]
        for channel in channels:
            if channel is not None:
                channel.stop()
        # one shared deadline across all channel joins (same pattern as the
        # proc reap below): N wedged channels must not serialize into
        # N x timeout_s before workers even see SIGTERM
        join_deadline = time.monotonic() + timeout_s
        for channel in channels:
            if channel is not None:
                channel.join(
                    max(0.1, join_deadline - time.monotonic()))
        with self._requests_lock:
            leftovers = list(self._requests.values())
        for req in leftovers:
            if self.journal.fail(req.id, "supervisor shutdown"):
                self.counter("failed").inc()
                if not req.future.done():
                    req.future.set_exception(RequestFailedError(
                        "supervisor shut down before the request completed"))
            self._finish(req.id)
        for index, proc in procs:
            if proc is not None and proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError as e:
                    R.log_event("fleet_term_error", worker=index,
                                error=repr(e))
                    R.bump_counter("fleet_term_errors")
        deadline = time.monotonic() + timeout_s
        for index, proc in procs:
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                R.log_event("fleet_worker_drain_timeout", worker=index)
                try:
                    proc.kill()
                    proc.wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired) as e:
                    R.log_event("fleet_kill_error", worker=index,
                                error=repr(e))
                    R.bump_counter("fleet_kill_errors")
        if self._monitor is not None:
            self._monitor.join(timeout=5 * self._poll_s)
        if self._scraper is not None:
            # the loop's wait() observes _shutdown within one scrape period;
            # an in-flight scrape is bounded by its socket timeout
            self._scraper.join(timeout=self.cfg.fleet.scrape_period_s
                               + 2 * self.cfg.fleet.scrape_timeout_s)
        self.journal.close()

    # -- introspection -------------------------------------------------------

    def status(self) -> dict:
        d = {
            "role": "supervisor",
            "health": self.health(),
            "draining": self._draining.is_set(),
            "queue_depth": self.queue.depth(),
            "workers": [s.snapshot() for s in self._slots],
            "workers_alive": sum(1 for s in self._slots if s.state == ALIVE),
            "journal": self.journal.counts(),
            "fleet": {k[len("fleet/"):]: v for k, v in
                      tracing.registry().counters("fleet/").items()},
        }
        d["latency_ms"] = {k: round(v * 1000.0, 3) for k, v in
                           self.metrics.latency.percentiles((50, 99)).items()}
        d["queue_wait_ms"] = {k: round(v * 1000.0, 3) for k, v in
                              self.metrics.queue_wait.percentiles((50, 99)).items()}
        return d


class _FleetMetrics:
    """Latency/queue-wait reservoirs plus the typed-rejection counters; the
    monotonic fleet counters live directly in the telemetry registry
    (``dcr_fleet_*`` in Prometheus text)."""

    def __init__(self):
        self.latency = LatencyTracker(name="fleet/request_latency_s")
        self.queue_wait = LatencyTracker(name="fleet/queue_wait_s")

    def note_rejected(self, error: AdmissionError) -> None:
        tracing.registry().counter(
            f"fleet/rejected_{type(error).__name__}").inc()

"""Thread-safe request queue with bounded-depth admission control.

The admission contract is the first line of overload defense: a request
either enters the bounded queue or is rejected *immediately* with a typed
error the HTTP layer maps to 503 — latency under overload stays flat instead
of growing with queue depth, and a drain (SIGTERM) flips the queue closed so
no new work can sneak in behind the in-flight batches.

Requests carry their generation bucket (:class:`GenBucket`) so the batcher
can only ever co-schedule requests that share one compiled program.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, NamedTuple, Optional


class AdmissionError(RuntimeError):
    """Base class for typed request rejections."""


class QueueFullError(AdmissionError):
    """Pending depth is at the admission bound — the service is overloaded
    (HTTP 503)."""


class DrainingError(AdmissionError):
    """The service is draining (SIGTERM seen): no new admissions (HTTP 503)."""


class InvalidRequestError(AdmissionError):
    """The request's bucket parameters are invalid for this model — a client
    error (HTTP 400), rejected before any compile or device work."""


class BucketLimitError(AdmissionError):
    """Admitting this request would compile a new sampler beyond the
    configured resident-program budget (HTTP 503). Compiled programs are
    never evicted, so without this bound a client cycling novel bucket
    parameters could grow device/host memory without limit."""


class MemoryBudgetError(AdmissionError):
    """Admitting this request's NOVEL bucket would compile a resident
    program whose estimated footprint (from the live surfaces' XLA memory
    analysis, obs/memwatch.py) exceeds remaining device memory (HTTP 503).
    The containment that keeps one adversarial bucket request from OOMing a
    warm worker; buckets already resident are unaffected."""


class SloShedError(AdmissionError):
    """The fleet is shedding load: queue-wait p99 breached the configured SLO
    while a backlog exists (HTTP 503 with a Retry-After hint). Distinct from
    :class:`QueueFullError` — the queue has room, but anything admitted now
    would wait past the latency objective anyway."""

    def __init__(self, msg: str, retry_after_s: float = 5.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class NoWorkersError(AdmissionError):
    """No fleet worker has joined (yet), so an admitted request could not be
    dispatched anywhere (HTTP 503 with Retry-After — workers are compiling
    or respawning; balancers should retry shortly)."""

    def __init__(self, msg: str, retry_after_s: float = 5.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class GenBucket(NamedTuple):
    """The static generation parameters one compiled sampler serves. Two
    requests batch together iff their buckets are equal — everything here is
    baked into the jitted program as a Python constant.

    ``fast_ratio``/``fast_order`` select the training-free fast-sampling
    plan (dcr_tpu/sampling/fastsample.py): the per-step full|reuse schedule
    is derived from (steps, fast_ratio) on the host and baked into the
    program, so a fast bucket is a DISTINCT compiled program and the plan
    is batch-uniform by construction — the alone-vs-mixed-batch bit-identity
    contract holds with fast sampling on. ``fast_ratio=0`` is the dense
    (pre-fast, bit-identical) sampler. Defaults keep 5-field constructors
    and old 5-element wire tuples meaning exactly what they used to."""

    resolution: int
    steps: int
    guidance: float
    sampler: str
    rand_noise_lam: float
    fast_ratio: float = 0.0
    fast_order: int = 2


_req_ids = itertools.count(1)


@dataclass
class Request:
    """One admitted generation request. ``future`` resolves to a float32
    [H, W, 3] numpy image in [0, 1] (or an exception)."""

    prompt: str
    seed: int
    bucket: GenBucket
    id: int = field(default_factory=lambda: next(_req_ids))
    future: Future = field(default_factory=Future)
    enqueued_at: float = 0.0          # time.monotonic, stamped on admission
    cache_hit: Optional[bool] = None  # filled by the worker
    # copy-risk verdict (obs/copyrisk.RiskScore.doc), filled by the worker
    # after the device step when a risk index is loaded; None = unscored
    # (scoring disabled / still loading / scoring failed)
    risk: Optional[dict] = None
    # tracing.SpanHandle for the serve/request root span (opened at
    # admission, ended when the future resolves); child spans — queue wait,
    # device step, respond — parent on its id, giving one span tree per
    # request id across the handler and worker threads
    span: Any = None
    # distributed trace id (tracing.new_trace_id / the supervisor's wire
    # context): constant across requeues and across processes, so a fleet
    # request's supervisor-side and worker-side spans merge into one tree
    trace_id: Optional[str] = None


class RequestQueue:
    """Bounded FIFO with bucket-aware group pops.

    All methods are thread-safe; HTTP handler threads submit while the single
    worker thread pops. ``close()`` permanently stops admission (drain) but
    pops continue until empty — that ordering is what makes "SIGTERM finishes
    in-flight work" true.
    """

    def __init__(self, maxsize: int):
        if maxsize < 1:
            raise ValueError(f"queue maxsize must be >= 1, got {maxsize}")
        self.maxsize = maxsize
        self._items: list[Request] = []
        self._cond = threading.Condition()
        self._closed = False

    # -- producer side -------------------------------------------------------

    def submit(self, req: Request) -> None:
        """Admit or reject-with-type. Never blocks."""
        with self._cond:
            if self._closed:
                raise DrainingError("service is draining; not accepting requests")
            if len(self._items) >= self.maxsize:
                raise QueueFullError(
                    f"admission queue full ({self.maxsize} pending)")
            req.enqueued_at = time.monotonic()
            self._items.append(req)
            self._cond.notify_all()

    def requeue(self, reqs: list[Request]) -> None:
        """Put already-ACCEPTED requests back at the HEAD of the queue, in
        order (fleet supervisor path: their worker died mid-batch). Bypasses
        both the admission bound and the closed flag deliberately — these
        requests were admitted once and the zero-drop contract says they
        complete even during a drain; their original ``enqueued_at`` stamps
        are preserved so queue-wait telemetry and the batcher's deadline see
        the true wait, not a reset clock."""
        if not reqs:
            return
        with self._cond:
            self._items[:0] = reqs
            self._cond.notify_all()

    def close(self) -> None:
        """Stop admission permanently (drain). Idempotent."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    # -- consumer side -------------------------------------------------------

    def depth(self) -> int:
        with self._cond:
            return len(self._items)

    def has_bucket(self, bucket: GenBucket) -> bool:
        """Whether any PENDING request carries ``bucket`` — the admission
        rollback's guard: a bucket another thread's queued request still
        references must keep its resident-program slot (and its dcr-hbm
        byte reservation) registered."""
        with self._cond:
            return any(r.bucket == bucket for r in self._items)

    def empty(self) -> bool:
        return self.depth() == 0

    def head_age(self) -> float:
        """Seconds the oldest pending request has waited (0.0 when empty)."""
        with self._cond:
            if not self._items:
                return 0.0
            return time.monotonic() - self._items[0].enqueued_at

    def head_group_size(self) -> int:
        """How many pending requests share the head request's bucket."""
        with self._cond:
            if not self._items:
                return 0
            b = self._items[0].bucket
            return sum(1 for r in self._items if r.bucket == b)

    def wait_nonempty(self, timeout: float) -> bool:
        """Block up to ``timeout`` for any pending request; wakes early on
        close() too (drain must not wait out an idle timeout), but only
        returns True when something is actually pending."""
        with self._cond:
            self._cond.wait_for(lambda: bool(self._items) or self._closed,
                                timeout)
            return bool(self._items)

    def wait_change(self, timeout: float) -> None:
        """Block up to ``timeout`` for any queue state change (new submit or
        close) — the batcher's fill-wait primitive."""
        with self._cond:
            self._cond.wait(timeout)

    def take_group(self, max_n: int) -> list[Request]:
        """Pop up to ``max_n`` requests sharing the head's bucket, preserving
        FIFO order within the group AND for the requests left behind."""
        with self._cond:
            if not self._items:
                return []
            b = self._items[0].bucket
            out, keep = [], []
            for r in self._items:
                if r.bucket == b and len(out) < max_n:
                    out.append(r)
                else:
                    keep.append(r)
            self._items = keep
            return out

"""Fleet metrics aggregation: scrape worker registries, merge expositions.

Before dcr-scope the front end's ``/metrics`` showed only the supervisor's
own accounting — every worker's cache hit rate, compile count, fault
counters and device-step latency summary were invisible unless an operator
curled N internal ports by hand. This module gives the supervisor a
Prometheus-style pull model over its own fleet:

- :class:`ScrapeCache` polls each ALIVE worker's
  ``/metrics?format=prometheus`` on a bounded-timeout loop (socket-level
  timeout: a dead or wedged worker costs at most ``timeout_s``, never a
  hang) and keeps the **last good** text per worker with its scrape time;
- :func:`inject_labels` rewrites every sample line of an exposition with a
  ``worker="N"`` label so merged series stay distinguishable;
- :func:`merge_expositions` concatenates sections while deduplicating
  ``# HELP``/``# TYPE`` headers (the format allows each metric's header
  once per exposition).

Staleness is first-class, not hidden: the merged document always carries
``dcr_fleet_worker_up{worker="N"}`` and
``dcr_fleet_worker_scrape_age_seconds{worker="N"}`` per slot, so a scrape
of the supervisor distinguishes "worker 3 is dead, these are its last
numbers" from "worker 3 is fine".

Pure stdlib; the label/merge helpers are pure functions (unit-tested
without sockets).
"""

from __future__ import annotations

import http.client
import threading
import time
from typing import Optional

from dcr_tpu.core import resilience as R
from dcr_tpu.core import tracing
from dcr_tpu.core.tracing import sanitize_label_name


def http_get_text(host: str, port: int, path: str,
                  timeout_s: float) -> tuple[int, str]:
    """One bounded GET over a fresh connection; (status, body text)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", "replace")
    finally:
        conn.close()


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def inject_labels(text: str, labels: dict[str, str]) -> str:
    """Add ``labels`` to every sample line of a Prometheus exposition.

    Comment/blank lines pass through; existing label sets are extended
    (``m{quantile="0.99"}`` -> ``m{quantile="0.99",worker="1"}``). Label
    names are sanitized into valid identifiers, values escaped."""
    rendered = ",".join(
        f'{sanitize_label_name(k)}="{_escape_label_value(str(v))}"'
        for k, v in sorted(labels.items()))
    if not rendered:
        return text
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:           # malformed line: pass through untouched
            out.append(line)
            continue
        if name_part.endswith("}") and "{" in name_part:
            base, _, existing = name_part.partition("{")
            existing = existing[:-1]
            sep = "," if existing else ""
            out.append(f"{base}{{{existing}{sep}{rendered}}} {value_part}")
        else:
            out.append(f"{name_part}{{{rendered}}} {value_part}")
    return "\n".join(out) + "\n"


def merge_expositions(sections: list[str]) -> str:
    """Concatenate exposition sections, keeping each metric's ``# HELP`` /
    ``# TYPE`` header only the first time it appears (the text format allows
    one header per metric per exposition; sample lines with distinct label
    sets are exactly how multi-worker series coexist)."""
    seen_headers: set[tuple[str, str]] = set()
    out: list[str] = []
    for section in sections:
        for line in section.splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                kind, _, rest = line[2:].partition(" ")
                metric = rest.split(" ", 1)[0]
                if (kind, metric) in seen_headers:
                    continue
                seen_headers.add((kind, metric))
            elif not line:
                continue
            out.append(line)
    return "\n".join(out) + "\n"


class ScrapeCache:
    """Last-good-text cache over the fleet's internal metrics ports.

    ``scrape()`` is called by the supervisor's scrape loop for each live
    worker; ``snapshot()`` is called by the ``/metrics`` handler and never
    blocks on the network — a dead worker surfaces as a growing
    ``scrape_age`` on its cached section, not a hanging scrape of the
    supervisor itself."""

    def __init__(self, host: str, timeout_s: float):
        self.host = host
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._cache: dict[int, tuple[str, float]] = {}   # index -> (text, t)

    def scrape(self, index: int, port: int) -> bool:
        try:
            status, text = http_get_text(
                self.host, port, "/metrics?format=prometheus", self.timeout_s)
        except (OSError, http.client.HTTPException) as e:
            R.log_trace("fleet_scrape_failed", worker=index, error=repr(e))
            tracing.registry().counter("fleet/scrape_errors").inc()
            return False
        if status != 200:
            R.log_event("fleet_scrape_bad_status", worker=index, status=status)
            tracing.registry().counter("fleet/scrape_errors").inc()
            return False
        with self._lock:
            self._cache[index] = (text, time.time())
        tracing.registry().counter("fleet/scrapes").inc()
        return True

    def forget(self, index: int) -> None:
        """Drop a retired slot's section (a respawned incarnation repopulates
        it on the next successful scrape)."""
        with self._lock:
            self._cache.pop(index, None)

    def snapshot(self) -> dict[int, tuple[str, float]]:
        """{index: (last good exposition text, age seconds)}."""
        now = time.time()
        with self._lock:
            return {i: (text, now - t) for i, (text, t) in self._cache.items()}

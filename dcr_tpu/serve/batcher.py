"""Deadline-aware dynamic batching policy.

The worker keeps one compiled sampler per (bucket, max_batch) and every batch
runs at exactly that padded shape, so the batching decision is purely *when*
to flush, never *what shape* to compile:

- flush as soon as a full ``max_batch`` group is pending (throughput), or
- flush a partial group once its oldest request has waited ``max_wait_s``
  (the latency deadline — a lone request never waits more than one
  max-wait for company), or
- flush immediately during drain (stop/closed), so SIGTERM finishes the
  backlog at partial occupancy instead of idling out each max-wait.

:func:`should_flush` is the pure decision function (unit-tested directly);
:class:`Batcher` wires it to a live :class:`~dcr_tpu.serve.queue.RequestQueue`.
"""

from __future__ import annotations

import threading
from typing import Optional

from dcr_tpu.serve.queue import Request, RequestQueue


def should_flush(group_size: int, max_batch: int, oldest_age_s: float,
                 max_wait_s: float, *, draining: bool = False) -> bool:
    """Flush decision for the head bucket group. Pure — no clock, no locks."""
    if group_size <= 0:
        return False
    if group_size >= max_batch:
        return True
    if draining:
        return True
    return oldest_age_s >= max_wait_s


class Batcher:
    """Pulls bucket-coherent batches out of a :class:`RequestQueue`.

    ``next_batch`` blocks until it can return a non-empty batch, or returns
    ``None`` once ``stop`` is set and the queue is fully drained — the worker
    loop's termination signal.
    """

    def __init__(self, max_batch: int, max_wait_s: float, *,
                 poll_s: float = 0.005, idle_wait_s: float = 0.5):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = max_batch
        self.max_wait_s = float(max_wait_s)
        # fill-wait granularity while a partial batch is pending (bounded by
        # max_wait_s, so the fine poll only runs when there is work)
        self.poll_s = poll_s
        # idle block: submit()/close() notify the queue's condition, so a
        # long timeout costs no latency — it only bounds how often an idle
        # worker wakes to re-check the stop event
        self.idle_wait_s = idle_wait_s

    def next_batch(self, queue: RequestQueue,
                   stop: Optional[threading.Event] = None) -> Optional[list[Request]]:
        stop = stop or threading.Event()
        while True:
            if not queue.wait_nonempty(self.idle_wait_s):
                if stop.is_set() and queue.empty():
                    return None
                continue
            # fill-wait: hold the head group until it is full, its deadline
            # passes, or the service starts draining
            while not should_flush(queue.head_group_size(), self.max_batch,
                                   queue.head_age(), self.max_wait_s,
                                   draining=stop.is_set() or queue.closed):
                if queue.empty():        # raced with another consumer
                    break
                queue.wait_change(self.poll_s)
            batch = queue.take_group(self.max_batch)
            if batch:
                return batch

"""Serve-side live ingest: stream scored generations into the store.

The bridge between copy-risk scoring and the dcr-live WAL tier
(:mod:`dcr_tpu.search.livestore`): every generation the worker scores
already has its SSCD embedding in hand, so :class:`IngestPump` enqueues
``(embedding, key)`` on a bounded queue and a background appender thread
makes them durable. The response path calls :meth:`IngestPump.offer` and
NOTHING else — it never blocks, never touches the filesystem, and when
the queue is full the row is dropped-and-counted
(``ingest/dropped_total``), because a slow disk must degrade provenance
coverage, not generation latency (the bench_ingest p99 gate).

The appender owns the store's writer lease. If another process holds it
(a previous worker incarnation that hasn't expired yet), the pump sits in
``waiting_lease`` and retries on a timed wait until the stale lease ages
out and is taken over — the same self-healing story as the fleet worker
lease. Every ``compact_rows`` acked-but-unfolded rows it compacts
(``prune=False``), tells the worker to refresh its risk engine onto the
new snapshot, then prunes — so in-flight ``/check`` queries keep the
snapshot they started with and no row is ever served twice or missed.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from pathlib import Path
from typing import Callable, Optional

import numpy as np

from dcr_tpu.core import tracing
from dcr_tpu.search.livestore import DEFAULT_SEAL_ROWS, LiveStore
from dcr_tpu.search.store import (DEFAULT_LEASE_S, StoreError,
                                  StoreLeaseHeldError)
from dcr_tpu.utils import faults

log = logging.getLogger("dcr_tpu")

#: default bound on the response-path queue (rows, not batches)
DEFAULT_QUEUE_MAX = 1024


class IngestPump:
    """Bounded-queue, never-blocks producer + durable appender thread."""

    def __init__(self, store_dir: str | Path, *, embed_dim: int = 512,
                 queue_max: int = DEFAULT_QUEUE_MAX, batch_rows: int = 16,
                 seal_rows: int = DEFAULT_SEAL_ROWS,
                 compact_rows: int = 0, lease_s: float = DEFAULT_LEASE_S,
                 owner: str = "",
                 on_snapshot: Optional[Callable[[int], None]] = None):
        self.dir = Path(store_dir)
        self.embed_dim = int(embed_dim)
        self.batch_rows = max(1, int(batch_rows))
        self.seal_rows = int(seal_rows)
        self.compact_rows = int(compact_rows)  # 0 = never auto-compact
        self.lease_s = float(lease_s)
        self.owner = owner or f"ingest-pump.{self.dir.name}"
        self.on_snapshot = on_snapshot
        self._q: "queue.Queue[tuple[float, np.ndarray, str]]" = queue.Queue(
            maxsize=max(1, int(queue_max)))
        self._stop = threading.Event()
        self._live: Optional[LiveStore] = None
        self._thread: Optional[threading.Thread] = None
        # guards the appender-thread-written telemetry below (status,
        # counters, last_error, _live) against the stats()/compact_now()
        # readers; the offer() hot path never takes it
        self._stats_lock = threading.Lock()
        self.status = "starting"
        self.appended_rows = 0
        self.dropped_rows = 0
        self.compactions = 0
        self.last_error = ""

    # -- response path (hot): never blocks -----------------------------------

    def offer(self, features_row: np.ndarray, key: str) -> bool:
        """Enqueue one embedding row for durable append. Non-blocking by
        construction (``put_nowait``): a full queue means the row is
        dropped and counted, never a stalled response."""
        row = np.asarray(features_row, np.float32).reshape(-1)
        try:
            self._q.put_nowait((time.time(), row, str(key)))
        except queue.Full:
            self.dropped_rows += 1
            tracing.registry().counter("ingest/dropped_total").inc()
            return False
        tracing.registry().gauge("ingest/queue_depth").set(self._q.qsize())
        return True

    # -- appender thread ------------------------------------------------------

    def start(self) -> "IngestPump":
        self._thread = threading.Thread(target=self._run, name="ingest-pump",
                                        daemon=True)
        self._thread.start()
        return self

    def _open_with_retry(self) -> Optional[LiveStore]:
        while not self._stop.is_set():
            try:
                live = LiveStore.open(self.dir, embed_dim=self.embed_dim,
                                      seal_rows=self.seal_rows,
                                      lease_s=self.lease_s, owner=self.owner)
                with self._stats_lock:
                    self.status = "ok"
                return live
            except StoreLeaseHeldError as e:
                # another writer (likely our crashed predecessor) still
                # holds the lease — wait out its heartbeat, then take over
                with self._stats_lock:
                    self.status = "waiting_lease"
                    self.last_error = str(e)
                tracing.registry().counter(
                    "ingest/lease_wait_total").inc()
                self._stop.wait(max(0.5, self.lease_s / 4))
            except StoreError as e:
                with self._stats_lock:
                    self.status = "error"
                    self.last_error = str(e)
                log.error("ingest: cannot open live store %s: %s",
                          self.dir, e)
                return None
        return None

    def _drain_batch(self, first) -> tuple[float, np.ndarray, list[str]]:
        items = [first]
        while len(items) < self.batch_rows:
            try:
                items.append(self._q.get_nowait())
            except queue.Empty:
                break
        feats = np.stack([row for _, row, _ in items])
        keys = [k for _, _, k in items]
        return items[0][0], feats, keys

    def _run(self) -> None:
        live = self._open_with_retry()
        if live is None:
            return
        with self._stats_lock:
            self._live = live
        reg = tracing.registry()
        try:
            while True:
                try:
                    first = self._q.get(timeout=0.2)
                except queue.Empty:
                    if self._stop.is_set():
                        break
                    reg.gauge("ingest/lag_seconds").set(0.0)
                    reg.gauge("ingest/queue_depth").set(0)
                    # keep the age/growth gauges moving between appends —
                    # a quiet pump with a stale unfolded row must still age
                    live.update_lag_gauges()
                    continue
                oldest_ts, feats, keys = self._drain_batch(first)
                if faults.fire("ingest_stall", row=self.appended_rows):
                    self._stall(reg, oldest_ts)
                try:
                    live.append(feats, keys)
                    with self._stats_lock:
                        self.appended_rows += feats.shape[0]
                except StoreError as e:
                    # includes the injected wal_torn frame: not acked, the
                    # batch is lost-and-counted, the pump keeps pumping
                    with self._stats_lock:
                        self.last_error = str(e)
                    reg.counter("ingest/append_failed_total").inc(
                        feats.shape[0])
                    log.warning("ingest: append failed (%d rows): %s",
                                feats.shape[0], e)
                reg.gauge("ingest/lag_seconds").set(
                    max(0.0, time.time() - oldest_ts))
                reg.gauge("ingest/queue_depth").set(self._q.qsize())
                if (self.compact_rows > 0
                        and live.total_rows - live.committed_total
                        >= self.compact_rows):
                    self._compact(live)
        finally:
            with self._stats_lock:
                self._live = None
            live.close()
            with self._stats_lock:
                if self.status == "ok":
                    self.status = "stopped"

    def _stall(self, reg, oldest_ts: float) -> None:
        """Injected ``ingest_stall`` fault: the pump stops acking for
        ``DCR_INGEST_STALL_S`` seconds while the lag gauges keep reporting
        the truth (that is the point — the SLO plane must SEE the stall).
        Rows are delayed, never dropped: the batch appends after the stall,
        so recovery is a clean breach -> ok round trip with zero loss."""
        stall_s = float(os.environ.get("DCR_INGEST_STALL_S", "30"))
        with self._stats_lock:
            self.status = "stalled"
        tracing.event("ingest/stall", stall_s=stall_s,
                      row=self.appended_rows)
        log.warning("ingest: injected stall for %.1fs at row %d",
                    stall_s, self.appended_rows)
        deadline = time.monotonic() + stall_s
        while not self._stop.is_set() and time.monotonic() < deadline:
            reg.gauge("ingest/lag_seconds").set(
                max(0.0, time.time() - oldest_ts))
            reg.gauge("ingest/queue_depth").set(self._q.qsize())
            self._stop.wait(0.1)
        with self._stats_lock:
            self.status = "ok"

    def _compact(self, live: LiveStore) -> None:
        try:
            report = live.compact(prune=False)
        except StoreError as e:
            with self._stats_lock:
                self.last_error = str(e)
            log.error("ingest: compaction failed: %s", e)
            return
        with self._stats_lock:
            self.compactions += 1
        if self.on_snapshot is not None:
            try:
                # the worker swaps its risk engine onto the new snapshot
                # BEFORE we prune, so there is never a moment where a row
                # is in neither the engine nor the tail
                self.on_snapshot(int(report.get("snapshot", 0)))
            except Exception:
                log.exception("ingest: on_snapshot callback failed "
                              "(snapshot v%s)", report.get("snapshot"))
        live.prune()

    # -- introspection / lifecycle -------------------------------------------

    def compact_now(self) -> None:
        """Test/ops hook: force a compaction from the appender's context by
        lowering the threshold to the next append. Synchronous version for
        a quiesced pump."""
        with self._stats_lock:
            live = self._live
        if live is not None:
            self._compact(live)

    def stats(self) -> dict:
        with self._stats_lock:
            live = self._live
            doc = {"status": self.status, "queued": self._q.qsize(),
                   "appended_rows": self.appended_rows,
                   "dropped_rows": self.dropped_rows,
                   "compactions": self.compactions}
            last_error = self.last_error
        if last_error:
            doc["last_error"] = last_error
        if live is not None:
            doc.update(snapshot=live.snapshot, total_rows=live.total_rows,
                       tail_rows=live.tail_rows)
        return doc

    def tail(self, after_seq: int) -> tuple[np.ndarray, np.ndarray]:
        """Live-tail provider for :class:`CopyRiskIndex` — the acked rows
        newer than the caller's snapshot (empty until the store is open)."""
        with self._stats_lock:
            live = self._live
        if live is None:
            return (np.zeros((0, self.embed_dim), np.float32),
                    np.zeros((0,), dtype=object))
        return live.tail(after_seq)

    def stop(self, timeout: float = 10.0) -> None:
        """Drain-and-stop: the appender finishes the queued backlog (every
        acked row stays durable in the WAL — recovery replays it), then
        releases the lease."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, timeout))
        self._thread = None

    def __enter__(self) -> "IngestPump":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

"""Fleet control plane: heartbeat-leased membership + the request journal.

Two pieces of durable, inspectable state make multi-worker serving
(:mod:`dcr_tpu.serve.supervisor`) fault-tolerant:

- **Worker leases** — a fleet worker "joins" by publishing a small JSON
  lease (pid, HTTP port, vae scale) into the fleet directory and renewing it
  every ``fleet.heartbeat_s``; a lease silent for ``fleet.lease_s`` is dead
  membership, whatever the process table says. This is the same
  publish/renew/expire shape as the PR 2 coordination-service KV control
  plane, but deliberately file-backed: jax's coordination service couples
  every client's liveness to the job (a lapsed client poisons the service
  and jaxlib SIGABRTs the survivors — the exact coupling a fleet that
  *expects* worker deaths must not have), while lease files survive any
  subset of processes dying and are readable by out-of-process tools (the
  chaos bench finds its kill targets here).
- **Request journal** — the supervisor's append-only JSONL record of every
  accepted request's lifecycle: ``add`` (admitted) → ``dispatch`` (sent to a
  worker) → ``ack`` (response delivered) | ``requeue`` (worker died
  mid-flight; the request goes back to the queue head) | ``fail`` (attempts
  exhausted — a typed 500, never a silent drop). The in-memory view drives
  requeue/duplicate-completion decisions; the file is the audit trail the
  zero-dropped-requests acceptance check replays.

Everything here is stdlib + wall-clock only: leases cross process
boundaries, so ``time.time()`` (one host, one clock) is the correct base,
not per-process ``monotonic``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

from dcr_tpu.core import fsio
from dcr_tpu.core import resilience as R
from dcr_tpu.serve.queue import GenBucket, Request


# ---------------------------------------------------------------------------
# Fleet directory layout
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FleetPaths:
    """Canonical layout of a fleet control-plane directory."""

    root: Path

    @property
    def leases(self) -> Path:
        return self.root / "leases"

    @property
    def journal(self) -> Path:
        return self.root / "journal.jsonl"

    @property
    def config(self) -> Path:
        return self.root / "config.json"

    @property
    def logs(self) -> Path:
        return self.root / "logs"

    def lease_file(self, index: int) -> Path:
        return self.leases / f"worker_{index}.json"

    def worker_log(self, index: int) -> Path:
        return self.logs / f"worker_{index}.log"

    def ensure(self) -> "FleetPaths":
        self.leases.mkdir(parents=True, exist_ok=True)
        self.logs.mkdir(parents=True, exist_ok=True)
        return self


def fleet_paths(root: str | Path) -> FleetPaths:
    return FleetPaths(Path(root))


# ---------------------------------------------------------------------------
# Heartbeat-leased membership
# ---------------------------------------------------------------------------

@dataclass
class WorkerLease:
    """One worker's membership claim. ``renewed_at``/``lease_s`` define the
    liveness contract; ``port`` is how the supervisor's dispatch channel
    finds the worker (workers bind port 0 and publish the real port here —
    no pick-then-close races); ``vae_scale`` teaches the supervisor the
    model's resolution granularity so it can fully validate buckets without
    loading the model itself."""

    index: int
    pid: int
    port: int
    vae_scale: int
    lease_s: float
    started_at: float = field(default_factory=time.time)
    renewed_at: float = field(default_factory=time.time)
    # warm-start readiness (dcr-warm): a worker publishes its lease EARLY
    # (so the supervisor can watch warming progress and spawn_timeout_s
    # covers the whole boot) with ready=False, then flips it once every
    # bucket in its warm plan is compiled. The supervisor only attaches a
    # dispatch channel to a ready lease — it never dispatches into a cold
    # worker. Defaults keep hand-written / pre-dcr-warm leases dispatchable.
    ready: bool = True
    buckets_warm: int = -1    # -1 = not reported
    buckets_total: int = -1
    # copy-risk index state (dcr-watch): absent | loading | ok | failed.
    # Rides the lease so the supervisor can (a) surface a worker whose
    # index load FAILED — it serves unscored, which must be visible, not
    # silent — and (b) route POST /check only to workers that can answer.
    # The default keeps pre-dcr-watch leases parseable.
    risk: str = "absent"

    def expired(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.time()) \
            > self.renewed_at + self.lease_s

    def age_s(self, now: Optional[float] = None) -> float:
        return (now if now is not None else time.time()) - self.renewed_at


def write_lease(paths: FleetPaths, lease: WorkerLease) -> Path:
    """Atomic publish/renew: write-to-temp + rename, so a reader never sees
    a torn lease (a corrupt control plane must be impossible by
    construction, not just unlikely). The temp name is per-THREAD, not just
    per-process: the heartbeat thread renews concurrently with the main
    thread's warm-ready flip, and a shared temp path would let one
    os.replace race the other into FileNotFoundError."""
    paths.leases.mkdir(parents=True, exist_ok=True)
    target = paths.lease_file(lease.index)
    tmp = target.with_suffix(
        f".tmp.{lease.pid}.{threading.get_ident()}")
    fsio.publish_durable(tmp, target,
                         json.dumps(vars(lease), sort_keys=True) + "\n")
    return target


def read_lease(paths: FleetPaths, index: int) -> Optional[WorkerLease]:
    """None when absent. A malformed lease is treated as absent but LOUDLY
    (structured log + counter): it means something other than write_lease
    touched the control plane."""
    target = paths.lease_file(index)
    try:
        raw = target.read_text()
    except FileNotFoundError:
        return None
    except OSError as e:
        R.log_event("fleet_lease_read_error", index=index, error=repr(e))
        R.bump_counter("fleet_lease_read_errors")
        return None
    try:
        return WorkerLease(**json.loads(raw))
    except (ValueError, TypeError) as e:
        R.log_event("fleet_lease_corrupt", index=index, error=repr(e))
        R.bump_counter("fleet_lease_corrupt")
        return None


def clear_lease(paths: FleetPaths, index: int) -> None:
    """Remove a dead worker's lease so a respawned incarnation's publish is
    unambiguous and external tools never target a stale pid."""
    try:
        paths.lease_file(index).unlink()
    except FileNotFoundError:
        return
    except OSError as e:
        R.log_event("fleet_lease_clear_error", index=index, error=repr(e))
        R.bump_counter("fleet_lease_clear_errors")


class LeaseHeartbeat:
    """Worker-side renewal thread: republish the lease every ``heartbeat_s``
    until stopped. Renewal is liveness of the PROCESS, not of the device
    step — a wedged sampler still renews, which is why hang detection
    belongs to the worker's own batch watchdog (exit 89) and the
    supervisor's dispatch timeout, and the lease only backstops a fully
    frozen/SIGSTOPped process."""

    def __init__(self, paths: FleetPaths, lease: WorkerLease,
                 heartbeat_s: float):
        self.paths = paths
        self.lease = lease
        self.heartbeat_s = float(heartbeat_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "LeaseHeartbeat":
        write_lease(self.paths, self.lease)      # join before the first beat
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"lease-heartbeat:{self.lease.index}")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            self.lease.renewed_at = time.time()
            try:
                write_lease(self.paths, self.lease)
            except OSError as e:
                # a missed renewal is survivable (the lease has slack);
                # a silent one is not
                R.log_event("fleet_lease_renew_error", index=self.lease.index,
                            error=repr(e))
                R.bump_counter("fleet_lease_renew_errors")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.heartbeat_s)
            self._thread = None


# ---------------------------------------------------------------------------
# Request journal
# ---------------------------------------------------------------------------

QUEUED = "queued"
IN_FLIGHT = "in_flight"
ACKED = "acked"
FAILED = "failed"


@dataclass
class JournalEntry:
    """In-memory lifecycle state of one accepted request."""

    id: int
    prompt: str
    seed: int
    bucket: tuple
    state: str = QUEUED
    worker: int = -1          # current/last dispatch target
    attempts: int = 0         # dispatches so far (1 = never requeued)
    charged: int = 0          # attempts counted against max_attempts: a
                              # worker-state rejection (drain/overload) is
                              # refunded — the request never executed there
    trace_id: str = ""        # distributed trace id: constant across
                              # requeues, so the journal links every dispatch
                              # attempt to one cross-process span tree


# How many terminal (acked/failed) entries the journal keeps addressable for
# late-completion dedup before evicting the oldest. Only a requeued twin
# still sitting in the bounded admission queue ever needs its terminal
# record, so this just has to comfortably exceed queue_depth + max in-flight;
# an evicted id's late completion is still dropped (unknown == duplicate).
_TERMINAL_KEEP = 4096


class RequestJournal:
    """Supervisor-side accounting that makes "kill a worker, lose no
    requests" checkable rather than hoped-for.

    State machine per request (enforced; violations raise — a supervisor
    bug must never silently corrupt the zero-drop ledger)::

        add -> QUEUED -> dispatch -> IN_FLIGHT -> ack  -> ACKED (terminal)
                  ^                      |
                  +------ requeue -------+--> fail -> FAILED (terminal)

    ``ack`` is first-wins: a second completion for the same id (the worker
    was presumed dead, its batch requeued, and then BOTH executions
    delivered) returns False and is counted as a duplicate, so exactly one
    response reaches the client. Every transition appends one JSONL line to
    the durable journal (when a path is given); :meth:`replay` rebuilds the
    final states from the file alone — the chaos bench's dropped-request
    count comes from there, not from in-process counters that die with the
    supervisor.
    """

    def __init__(self, path: Optional[str | Path] = None):
        self.path = Path(path) if path is not None else None
        # live (QUEUED/IN_FLIGHT) entries only: monitor/metrics scans are
        # O(backlog), not O(lifetime). Terminal entries move to the bounded
        # _terminal map (prompt dropped) so a week-long supervisor's RSS
        # doesn't grow with every request it ever served; the durable file
        # keeps the full history for replay().
        self._entries: dict[int, JournalEntry] = {}
        self._terminal: "collections.OrderedDict[int, JournalEntry]" = (
            collections.OrderedDict())
        self._accepted_total = 0
        self._acked_total = 0
        self._failed_total = 0
        self._lock = threading.Lock()
        self._file = None
        self.requeued_total = 0
        self.duplicate_acks = 0
        if self.path is not None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            # one file = one supervisor incarnation: request ids restart per
            # process, so appending a restarted supervisor's lifecycle onto a
            # previous run's file would let run 2's `add` for id N overwrite
            # run 1's terminal state in replay() and corrupt the zero-drop
            # arithmetic. A leftover file (restart wrapper reusing
            # --fleet.dir) is rotated aside, never merged into.
            if self.path.exists() and self.path.stat().st_size:
                os.replace(self.path,
                           self.path.with_name(
                               f"{self.path.name}.{int(time.time())}"
                               f".{os.getpid()}"))
            self._file = self.path.open("a", buffering=1)  # line-buffered

    # -- transitions ---------------------------------------------------------

    def _append(self, op: str, **fields: Any) -> None:
        if self._file is None:
            return
        rec = {"op": op, "t": time.time(), **fields}
        try:
            self._file.write(json.dumps(rec, sort_keys=True) + "\n")
        except OSError as e:
            # the in-memory ledger stays correct; losing the audit trail is
            # loud, not fatal to serving
            R.log_event("fleet_journal_write_error", op=op, error=repr(e))
            R.bump_counter("fleet_journal_write_errors")

    def add(self, req: Request) -> JournalEntry:
        with self._lock:
            if req.id in self._entries or req.id in self._terminal:
                raise ValueError(f"request {req.id} already journaled")
            e = JournalEntry(id=req.id, prompt=req.prompt, seed=req.seed,
                             bucket=tuple(req.bucket),
                             trace_id=getattr(req, "trace_id", None) or "")
            self._entries[req.id] = e
            self._accepted_total += 1
            self._append("add", id=req.id, prompt=req.prompt, seed=req.seed,
                         bucket=list(req.bucket), trace=e.trace_id)
            return e

    def reject(self, req_id: int, reason: str) -> None:
        """Remove a never-dispatched entry (admission rolled back after the
        journal line was written — e.g. the bounded queue was full). Keeps
        the zero-drop arithmetic honest: a rejected request was never
        accepted, so it must not linger as QUEUED forever."""
        with self._lock:
            e = self._entries.get(req_id)
            if e is None:
                return
            if e.state != QUEUED or e.attempts:
                raise ValueError(
                    f"reject of request {req_id} in state {e.state!r} "
                    f"after {e.attempts} dispatch(es)")
            del self._entries[req_id]
            self._accepted_total -= 1
            self._append("reject", id=req_id, reason=reason)

    def dispatch(self, req_id: int, worker: int) -> Optional[int]:
        """QUEUED -> IN_FLIGHT; returns the attempt number (1-based).
        Returns None — caller must skip the request — when the entry is
        already terminal: a requeued twin finished first while this copy
        waited in the queue. Double-dispatch (IN_FLIGHT) is a supervisor
        bug and raises."""
        with self._lock:
            e = self._entries.get(req_id)
            if e is None:
                if req_id in self._terminal:
                    return None
                raise KeyError(req_id)
            if e.state != QUEUED:
                raise ValueError(
                    f"dispatch of request {req_id} in state {e.state!r}")
            e.state, e.worker = IN_FLIGHT, worker
            e.attempts += 1
            e.charged += 1
            self._append("dispatch", id=req_id, worker=worker,
                         attempt=e.attempts)
            return e.attempts

    def requeue(self, req_id: int, worker: int, reason: str,
                charge: bool = True) -> int:
        """IN_FLIGHT -> QUEUED (worker died / dispatch failed); returns the
        attempts charged so far so the caller can enforce max_attempts.
        ``charge=False`` refunds this dispatch: the worker rejected the item
        because of ITS state (draining/overloaded) without executing it, so
        the bounce must not burn the request's budget — the rejecting worker
        retires from dispatch, so the fleet's respawn budget bounds how often
        this can recur."""
        with self._lock:
            e = self._entries.get(req_id)
            if e is None:
                state = (self._terminal[req_id].state
                         if req_id in self._terminal else "unknown")
                raise ValueError(
                    f"requeue of request {req_id} in state {state!r}")
            if e.state != IN_FLIGHT:
                raise ValueError(
                    f"requeue of request {req_id} in state {e.state!r}")
            e.state = QUEUED
            if not charge:
                e.charged -= 1
            self.requeued_total += 1
            self._append("requeue", id=req_id, worker=worker, reason=reason,
                         attempts=e.attempts, charged=e.charged)
            return e.charged

    def ack(self, req_id: int, worker: int) -> bool:
        """First completion wins: True exactly once per request. A False
        return means a duplicate/late completion (or an ack for a request
        already failed) — the caller must DROP that result."""
        with self._lock:
            e = self._entries.get(req_id)
            if e is None:
                self.duplicate_acks += 1
                self._append("duplicate_ack", id=req_id, worker=worker)
                return False
            e.state, e.worker = ACKED, worker
            self._acked_total += 1
            self._retire(e)
            self._append("ack", id=req_id, worker=worker)
            return True

    def fail(self, req_id: int, reason: str) -> bool:
        """Terminal failure (attempts exhausted / unrecoverable worker
        error). False when the request already completed — same first-wins
        contract as :meth:`ack`."""
        with self._lock:
            e = self._entries.get(req_id)
            if e is None:
                return False
            e.state = FAILED
            self._failed_total += 1
            self._retire(e)
            self._append("fail", id=req_id, reason=reason)
            return True

    def _retire(self, e: JournalEntry) -> None:
        """Move a now-terminal entry out of the live map (lock held). The
        prompt is dropped (only the audit file needs it) and the terminal
        map is capped: late completions for evicted ids are still dropped,
        because unknown == duplicate in :meth:`ack`."""
        del self._entries[e.id]
        e.prompt = ""
        self._terminal[e.id] = e
        while len(self._terminal) > _TERMINAL_KEEP:
            self._terminal.popitem(last=False)

    # -- views ---------------------------------------------------------------

    def entry(self, req_id: int) -> Optional[JournalEntry]:
        with self._lock:
            return self._entries.get(req_id) or self._terminal.get(req_id)

    def inflight_for(self, worker: int) -> list[int]:
        """Request ids currently dispatched to ``worker`` — the requeue set
        when its lease lapses (last-resort sweep; the dispatch channel's own
        error path normally requeues first)."""
        with self._lock:
            return [e.id for e in self._entries.values()
                    if e.state == IN_FLIGHT and e.worker == worker]

    def pending_count(self) -> int:
        with self._lock:
            return len(self._entries)   # live == QUEUED or IN_FLIGHT

    def counts(self) -> dict:
        with self._lock:
            by_state = {QUEUED: 0, IN_FLIGHT: 0,
                        ACKED: self._acked_total, FAILED: self._failed_total}
            for e in self._entries.values():
                by_state[e.state] += 1
            return {"accepted": self._accepted_total, **by_state,
                    "requeued_total": self.requeued_total,
                    "duplicate_acks": self.duplicate_acks}

    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError as e:
                R.log_event("fleet_journal_close_error", error=repr(e))
                R.bump_counter("fleet_journal_write_errors")
            self._file = None

    # -- offline audit -------------------------------------------------------

    @staticmethod
    def replay(path: str | Path) -> dict:
        """Rebuild final request states from the durable journal alone.

        Returns ``{"states": {id: state}, "counts": {...}}`` with the same
        count keys as :meth:`counts`. This is the acceptance arithmetic for
        chaos runs: ``dropped = accepted - acked - failed`` must be 0 (and
        ``failed`` must be 0 for a run whose churn stayed within the
        respawn/attempt budgets)."""
        states: dict[int, str] = {}
        requeued = duplicates = 0
        for line in Path(path).read_text().splitlines():
            if not line.strip():
                continue
            rec = json.loads(line)
            op, rid = rec["op"], rec.get("id")
            if op == "add":
                states[rid] = QUEUED
            elif op == "reject":
                states.pop(rid, None)    # admission rolled back: never accepted
            elif op == "dispatch":
                states[rid] = IN_FLIGHT
            elif op == "requeue":
                states[rid] = QUEUED
                requeued += 1
            elif op == "ack":
                states[rid] = ACKED
            elif op == "fail":
                states[rid] = FAILED
            elif op == "duplicate_ack":
                duplicates += 1
        by_state = {QUEUED: 0, IN_FLIGHT: 0, ACKED: 0, FAILED: 0}
        for s in states.values():
            by_state[s] += 1
        counts = {"accepted": len(states), **by_state,
                  "requeued_total": requeued, "duplicate_acks": duplicates}
        counts["dropped"] = counts["accepted"] - counts[ACKED] - counts[FAILED]
        return {"states": states, "counts": counts}


def bucket_from_tuple(values: tuple | list) -> GenBucket:
    """Inverse of ``tuple(bucket)`` for journal/wire round-trips. Accepts
    the pre-fast 5-element form too (warm manifests and journals written by
    older incarnations): missing fast fields default to the dense plan —
    exactly what those programs were."""
    res, steps, guidance, sampler, lam, *fast = values
    if fast and len(fast) != 2:
        raise ValueError(f"bucket tuple has {len(values)} elements, "
                         "expected 5 or 7")
    fast_ratio, fast_order = fast or (0.0, 2)
    return GenBucket(resolution=int(res), steps=int(steps),
                     guidance=float(guidance), sampler=str(sampler),
                     rand_noise_lam=float(lam),
                     fast_ratio=float(fast_ratio),
                     fast_order=int(fast_order))

"""Parameter partition rules: FSDP + Megatron-style tensor parallelism.

The reference has no TP (SURVEY.md §2.2 — optional GSPMD channel sharding
"later"); here it's a first-class option: transformer-block projections inside
the UNet shard over the `tensor` mesh axis (qkv/ff-in column-parallel, out/ff-out
row-parallel) and GSPMD inserts the matching collectives. Everything else
follows the FSDP largest-axis rule or replicates.
"""

from __future__ import annotations

import re

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dcr_tpu.parallel.mesh import TENSOR_AXIS, fsdp_spec

# column-parallel (shard the output features): qkv projections, ff up-projection
_COLUMN_PAT = re.compile(r"(to_q|to_k|to_v|ff/proj_in|qkv)/kernel$")
# row-parallel (shard the input features): attention out, ff down-projection
_ROW_PAT = re.compile(r"(to_out|ff/proj_out)/kernel$")


def _tp_spec(path: str, shape: tuple[int, ...], tensor: int):
    """PartitionSpec for a UNet param under tensor parallelism, or None."""
    if tensor <= 1 or len(shape) != 2:
        return None
    if _COLUMN_PAT.search(path) and shape[1] % tensor == 0:
        return P(None, TENSOR_AXIS)
    if _ROW_PAT.search(path) and shape[0] % tensor == 0:
        return P(TENSOR_AXIS, None)
    return None


def params_sharding(mesh: Mesh, params, *, tensor_parallel: bool = False,
                    min_fsdp_size: int = 2 ** 16):
    """NamedSharding tree: TP rules (when enabled) take precedence, then the
    shared FSDP largest-divisible-axis rule (mesh.fsdp_spec), else replicate."""
    tensor = mesh.shape[TENSOR_AXIS] if tensor_parallel else 1

    def spec_for(path_keys, x) -> NamedSharding:
        path = "/".join(str(getattr(k, "key", k)) for k in path_keys)
        shape = tuple(x.shape)
        tp = _tp_spec(path, shape, tensor)
        if tp is not None:
            return NamedSharding(mesh, tp)
        return NamedSharding(mesh, fsdp_spec(mesh, shape, min_fsdp_size))

    return jax.tree_util.tree_map_with_path(spec_for, params)

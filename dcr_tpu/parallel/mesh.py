"""Device mesh + sharding helpers — the single parallelism substrate.

Every boundary that is process+NCCL in the reference (DDP grad sync
diff_train.py:656, eval all_gather utils_ret.py:756-779) becomes a jit boundary
over this mesh: GSPMD inserts the ICI collectives. Axes:

  data    batch sharding (DP) — gradient psum rides ICI
  fsdp    parameter/optimizer sharding (ZeRO-3 style, all-gather on use)
  tensor  reserved for intra-layer sharding of the UNet (off by default)

Axes of size 1 are kept in the mesh so the same partition specs serve a single
chip, a v4-8, or a multi-host pod without code changes.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dcr_tpu.core.config import MeshConfig

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tensor"
SEQ_AXIS = "seq"
AXES = (DATA_AXIS, FSDP_AXIS, TENSOR_AXIS, SEQ_AXIS)


def make_mesh(cfg: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    d, f, t, s = cfg.axis_sizes(len(devices))
    arr = np.asarray(devices).reshape(d, f, t, s)
    return Mesh(arr, AXES)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Global batch sharded over data (and fsdp, which also consumes batch)."""
    return NamedSharding(mesh, P((DATA_AXIS, FSDP_AXIS)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_parallel_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]


def mesh_spans_processes(mesh: Mesh) -> bool:
    """True when the mesh's devices live on more than one process. Local
    meshes on a multi-process job (the lockstep-replica mode backends without
    cross-process XLA use) must take the single-host placement paths."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def shard_batch(mesh: Mesh, batch):
    """Place a host-global numpy batch onto the mesh, sharded on the batch axis.

    When the mesh spans processes each host passes its local shard;
    ``make_array_from_process_local_data`` assembles the global array. A
    local mesh (single process, or one replica of a multi-process CPU job)
    takes the plain device_put path.
    """
    sharding = batch_sharding(mesh)
    spans = mesh_spans_processes(mesh)

    def put(x):
        x = np.asarray(x)
        if spans:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree.map(put, batch)


def fsdp_spec(mesh: Mesh, shape: tuple[int, ...],
              min_size: int = 2 ** 16) -> PartitionSpec:
    """The FSDP rule: shard the largest evenly-divisible axis over `fsdp` when
    the tensor is big enough to be worth scattering, else replicate."""
    fsdp = mesh.shape[FSDP_AXIS]
    if fsdp > 1 and int(np.prod(shape, dtype=np.int64)) >= min_size:
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
        for i in order:
            if shape[i] % fsdp == 0:
                spec = [None] * len(shape)
                spec[i] = FSDP_AXIS
                return P(*spec)
    return P()


def fsdp_sharding_for_params(mesh: Mesh, params, min_size: int = 2 ** 16):
    """Pytree of NamedSharding matching `params` (arrays or ShapeDtypeStructs)
    under the FSDP rule."""
    return jax.tree.map(
        lambda x: NamedSharding(mesh, fsdp_spec(mesh, tuple(x.shape), min_size)),
        params)


def to_host(x) -> np.ndarray:
    """Fetch a (possibly globally-sharded) device array to host numpy on every
    process. Single-process: plain device_get. Multi-host: the array's shards
    are not all addressable locally, so all-gather across processes first."""
    if jax.process_count() == 1:
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils

    from dcr_tpu.core import dist

    # bounded: a host that died mid-eval turns this into a BarrierTimeout
    # with a name, instead of every surviving rank hanging in the gather
    return np.asarray(dist.run_with_timeout(
        lambda: multihost_utils.process_allgather(x, tiled=True),
        dist.default_allgather_timeout_s(), name="to_host"))


@contextmanager
def use_mesh(mesh: Mesh):
    with jax.sharding.use_mesh(mesh):
        yield mesh

from dcr_tpu.parallel import mesh  # noqa: F401
from dcr_tpu.parallel.mesh import (  # noqa: F401
    AXES,
    DATA_AXIS,
    FSDP_AXIS,
    SEQ_AXIS,
    TENSOR_AXIS,
    batch_sharding,
    data_parallel_size,
    fsdp_sharding_for_params,
    make_mesh,
    replicated,
    shard_batch,
    to_host,
    use_mesh,
)

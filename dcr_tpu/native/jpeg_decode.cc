// JPEG decode + box-downscale helper for the host input pipeline.
//
// Role: the training data loader decodes+resizes every sample on host CPU
// (reference: torchvision/PIL, datasets.py:59-67 — SURVEY.md §2.3 lists the
// decode path as one of the native dependencies to replace). libjpeg's
// DCT-domain scaling (scale_num/8) does most of a bilinear Resize for free
// during decode, which is the expensive part of feeding chips at bs=16×N
// (SURVEY.md §7.3 "host-side data pipeline throughput"). Python finishes the
// exact resize/crop on the much smaller intermediate.
//
// ctypes ABI (no pybind11 in this image):
//   jpeg_decode_scaled(buf, len, min_side, out_buf, out_cap, &w, &h) -> 0/-1
// out_buf receives H*W*3 RGB8; the chosen libjpeg scale is the smallest one
// whose shorter output side is still >= min_side (so Python's final resize
// only ever downscales, preserving quality).

#include <csetjmp>
#include <cstdio>
#include <cstring>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  std::longjmp(mgr->jump, 1);
}

}  // namespace

extern "C" {

long jpeg_decode_scaled(const unsigned char* data, long size, int min_side,
                        unsigned char* out, long out_capacity,
                        int* out_width, int* out_height) {
  if (!data || size <= 0 || !out || !out_width || !out_height) return -1;

  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;

  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }

  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = JCS_RGB;

  // pick the smallest DCT scale (8/8 .. 1/8) keeping shorter side >= min_side
  const int full_short =
      cinfo.image_width < cinfo.image_height ? cinfo.image_width
                                             : cinfo.image_height;
  int num = 8;
  if (min_side > 0) {
    for (int candidate = 1; candidate <= 8; ++candidate) {
      if (full_short * candidate / 8 >= min_side) {
        num = candidate;
        break;
      }
    }
  }
  cinfo.scale_num = static_cast<unsigned int>(num);
  cinfo.scale_denom = 8;

  jpeg_start_decompress(&cinfo);
  const long stride = static_cast<long>(cinfo.output_width) * 3;
  const long needed = stride * cinfo.output_height;
  if (needed > out_capacity) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + static_cast<long>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  *out_width = static_cast<int>(cinfo.output_width);
  *out_height = static_cast<int>(cinfo.output_height);
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // extern "C"

// JPEG encoded-size helper (libjpeg-turbo, in-memory encode, no file I/O).
//
// Role: the reference measures image complexity as the JPEG-compressed byte
// size via cv2.imencode (diff_retrieval.py:512-515). The eval loop calls this
// per matched training image; a native encode keeps the host-side metric pass
// off the Python critical path (SURVEY.md §2.3 names this the one first-party
// native component worth writing). Exposed through ctypes — no pybind11 in
// this environment.
//
// Build: see build.py (g++ -O2 -shared -fPIC jpeg_size.cc -ljpeg).

#include <csetjmp>
#include <cstddef>
#include <cstdio>  // jpeglib.h needs FILE declared before inclusion
#include <cstdlib>
#include <cstring>

#include <jpeglib.h>

namespace {

// libjpeg's default error_exit calls exit(), which would take down the host
// Python process; longjmp back instead so the wrapper returns -1.
struct ErrorMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* mgr = reinterpret_cast<ErrorMgr*>(cinfo->err);
  std::longjmp(mgr->jump, 1);
}

}  // namespace

extern "C" {

// Returns the encoded JPEG byte count for an RGB8 image, or -1 on error.
// data: H*W*3 interleaved RGB, rows top-down.
long jpeg_encoded_size(const unsigned char* data, int height, int width,
                       int quality) {
  if (data == nullptr || height <= 0 || width <= 0) return -1;

  jpeg_compress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;

  // The output pointer lives in a heap slot: locals modified between setjmp
  // and longjmp are indeterminate afterwards (C11 7.13.2.1), but the slot's
  // address is set before setjmp and libjpeg updates the slot contents.
  struct Slot {
    unsigned char* buffer = nullptr;
    unsigned long size = 0;
  };
  Slot* slot = new Slot();

  if (setjmp(jerr.jump)) {
    jpeg_destroy_compress(&cinfo);
    std::free(slot->buffer);
    delete slot;
    return -1;
  }

  jpeg_create_compress(&cinfo);
  jpeg_mem_dest(&cinfo, &slot->buffer, &slot->size);

  cinfo.image_width = static_cast<JDIMENSION>(width);
  cinfo.image_height = static_cast<JDIMENSION>(height);
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_RGB;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, quality, TRUE);

  jpeg_start_compress(&cinfo, TRUE);
  const size_t stride = static_cast<size_t>(width) * 3;
  while (cinfo.next_scanline < cinfo.image_height) {
    JSAMPROW row =
        const_cast<JSAMPROW>(data + cinfo.next_scanline * stride);
    jpeg_write_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);

  long out = static_cast<long>(slot->size);
  std::free(slot->buffer);
  delete slot;
  return out;
}

}  // extern "C"

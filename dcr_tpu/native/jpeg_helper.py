"""ctypes binding for the C++ JPEG encoded-size helper.

Builds lazily on first use (g++ is in the image; pybind11 is not, hence
ctypes). Falls back to None so callers (eval.complexity.jpeg_size) can use the
PIL path when the toolchain or libjpeg is absent.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

log = logging.getLogger("dcr_tpu")

_HERE = Path(__file__).parent
_SRC = _HERE / "jpeg_size.cc"
_LIB = _HERE / "libjpeg_size.so"
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    if not _LIB.exists():
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", str(_SRC), "-o", str(_LIB),
                 "-ljpeg"],
                check=True, capture_output=True, timeout=120)
        except Exception as e:
            log.info("native jpeg helper unavailable (%s); using PIL fallback", e)
            _build_failed = True
            return None
    try:
        lib = ctypes.CDLL(str(_LIB))
        lib.jpeg_encoded_size.restype = ctypes.c_long
        lib.jpeg_encoded_size.argtypes = [
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int, ctypes.c_int,
            ctypes.c_int]
        _lib = lib
        return lib
    except OSError as e:
        log.info("native jpeg helper failed to load (%s)", e)
        _build_failed = True
        return None


def encoded_size(image: np.ndarray, quality: int = 95) -> Optional[int]:
    """JPEG byte count for an HxWx3 uint8 array; None if the helper is
    unavailable (caller falls back to PIL)."""
    lib = _load()
    if lib is None:
        return None
    arr = np.ascontiguousarray(image, np.uint8)
    if arr.ndim != 3 or arr.shape[2] != 3:
        raise ValueError(f"expected HxWx3 uint8, got {arr.shape}")
    size = lib.jpeg_encoded_size(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        arr.shape[0], arr.shape[1], int(quality))
    return None if size < 0 else int(size)

"""First-party native helpers (C++, ctypes-bound)."""

"""ctypes binding for the C++ JPEG decode+scale helper.

Fast path for the host input pipeline: libjpeg decodes directly at the
smallest DCT scale whose shorter side still covers the target resolution, so
Python's exact resize works on a much smaller image. Falls back to None when
the toolchain/libjpeg is absent — callers use PIL then.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

log = logging.getLogger("dcr_tpu")

_HERE = Path(__file__).parent
_SRC = _HERE / "jpeg_decode.cc"
_LIB = _HERE / "libjpeg_decode.so"
_lib: Optional[ctypes.CDLL] = None
_build_failed = False
_load_lock = threading.Lock()  # DataLoader workers race first use


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    with _load_lock:
        if _lib is not None or _build_failed:
            return _lib
        if not _LIB.exists():
            tmp = _LIB.with_suffix(f".tmp{os.getpid()}.so")
            try:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", str(_SRC), "-o", str(tmp),
                     "-ljpeg"],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, _LIB)  # atomic: no partially written .so visible
            except Exception as e:
                log.info("native jpeg decoder unavailable (%s); using PIL", e)
                tmp.unlink(missing_ok=True)
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(str(_LIB))
            lib.jpeg_decode_scaled.restype = ctypes.c_long
            lib.jpeg_decode_scaled.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long, ctypes.c_int,
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long,
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
            _lib = lib
            return lib
        except OSError as e:
            log.info("native jpeg decoder failed to load (%s)", e)
            _build_failed = True
            return None


def available() -> bool:
    """Whether the fast path exists — check BEFORE reading file bytes so hosts
    without the toolchain don't pay a doubled read on every sample."""
    return _load() is not None


def decode_scaled(jpeg_bytes: bytes, min_side: int) -> Optional[np.ndarray]:
    """Decode JPEG bytes to an RGB8 [H,W,3] array whose shorter side is >=
    min_side (decoded at a reduced DCT scale when possible). None on any
    failure — caller falls back to PIL."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(jpeg_bytes, np.uint8)
    # capacity: full-size worst case (scale 8/8)
    # header parse is inside C; allocate generously from the byte length is not
    # possible, so use a first call convention: decode into a max-size buffer
    # derived from the SOF dimensions parsed cheaply here.
    dims = _parse_sof_dims(jpeg_bytes)
    if dims is None:
        return None
    w, h = dims
    out = np.empty(h * w * 3, np.uint8)
    ow, oh = ctypes.c_int(0), ctypes.c_int(0)
    rc = lib.jpeg_decode_scaled(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)), len(jpeg_bytes),
        int(min_side), out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
        out.nbytes, ctypes.byref(ow), ctypes.byref(oh))
    if rc != 0:
        return None
    return out[: oh.value * ow.value * 3].reshape(oh.value, ow.value, 3)


def _parse_sof_dims(data: bytes) -> Optional[tuple[int, int]]:
    """(width, height) from the JPEG SOF marker, header-only scan."""
    i = 2
    n = len(data)
    while i + 9 < n:
        if data[i] != 0xFF:
            return None
        marker = data[i + 1]
        if 0xC0 <= marker <= 0xCF and marker not in (0xC4, 0xC8, 0xCC):
            h = (data[i + 5] << 8) | data[i + 6]
            w = (data[i + 7] << 8) | data[i + 8]
            return (w, h)
        seg_len = (data[i + 2] << 8) | data[i + 3]
        i += 2 + seg_len
    return None
